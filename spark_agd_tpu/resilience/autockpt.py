"""Preemption-safe auto-checkpointing over ``utils.checkpoint``.

``utils.checkpoint.run_agd_checkpointed`` persists at fixed SEGMENT
boundaries; this module adds the operational half the north star needs
on preemptible capacity:

- **cadence**: save every N accumulated iterations and/or every T
  seconds, whichever fires first (both optional; ``force=True`` always
  saves) — so a slow segment cannot outrun the checkpoint budget;
- **retention**: the last K generations survive as a ``.bak`` chain
  (``path``, ``path.bak``, ``path.bak2`` …) rotated atomically before
  each write, so one torn write never erases the run;
- **corruption-tolerant load**: :meth:`load` walks the chain newest →
  oldest, skipping corrupt generations (typed
  ``CheckpointCorruptError``) and emitting one ``recovery`` record per
  skip — a truncated latest file resumes from the surviving
  generation;
- **preemption flush**: :meth:`install_signal_handlers` hooks
  SIGTERM/SIGINT; on delivery the last state handed to
  :meth:`update` is flushed to disk, a ``recovery`` record
  (``action="preemption_flush"``) is emitted, and
  :class:`~spark_agd_tpu.resilience.errors.Preempted` is raised into
  the main thread so drivers unwind — rerunning the same call resumes
  from the flushed carry.
"""

from __future__ import annotations

import os
import signal as signal_lib
import time
from typing import Any, Optional

import numpy as np

from ..utils import checkpoint as ckpt
from .errors import Preempted


def generation_paths(path: str, keep: int) -> list:
    """Newest-first retention chain: ``path``, ``path.bak``,
    ``path.bak2``, … (``keep`` entries total)."""
    out = [path]
    for i in range(1, keep):
        out.append(path + (".bak" if i == 1 else f".bak{i}"))
    return out


class AutoCheckpointer:
    """See module docstring.  ``telemetry`` (``obs.Telemetry``,
    optional) receives one ``recovery`` record per checkpoint written,
    generation skipped, and preemption flush.

    Thread/signal safety: :meth:`update` stores the latest state
    BEFORE testing cadence, so a signal arriving at any point flushes
    a state no older than the last completed segment.  The atomic
    write (tempfile + rename, ``utils.checkpoint.atomic_savez``) makes
    the flush itself kill-safe.
    """

    def __init__(self, path: str, *,
                 every_iters: Optional[int] = None,
                 every_seconds: Optional[float] = None,
                 keep: int = 2,
                 fingerprint: Optional[str] = None,
                 telemetry=None,
                 clock=time.monotonic):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        if every_iters is not None and every_iters < 1:
            raise ValueError("every_iters must be >= 1")
        if every_seconds is not None and every_seconds <= 0:
            raise ValueError("every_seconds must be > 0")
        self.path = path
        self.every_iters = every_iters
        self.every_seconds = every_seconds
        self.keep = keep
        self.fingerprint = fingerprint
        self.telemetry = telemetry
        self._clock = clock
        self._last_saved_iters: Optional[int] = None
        self._last_saved_t: Optional[float] = None
        self._latest = None  # (warm, hist, converged, aborted)
        self._prev_handlers = None
        self.saves = 0
        self.preempted = False
        # mid-epoch rider state (data.streaming.StreamCheckpoint): the
        # namespaced ``stream_*`` cursor entries the NEXT save carries,
        # the extras that rode the checkpoint :meth:`load` returned,
        # and the hook told about boundary commits / loaded extras
        self._extra = None
        self.loaded_extras = {}
        self.stream_hook = None

    # -- cadence ----------------------------------------------------------
    def _due(self, prior_iters: int) -> bool:
        if self._last_saved_iters is None:
            return True  # first state seen: establish generation zero
        if (self.every_iters is not None and
                prior_iters - self._last_saved_iters >= self.every_iters):
            return True
        if (self.every_seconds is not None and
                self._clock() - self._last_saved_t >= self.every_seconds):
            return True
        return False

    def update(self, warm, hist=None, *, converged: bool = False,
               aborted: bool = False, force: bool = False) -> bool:
        """Hand the checkpointer the newest carry; writes when the
        cadence is due (or ``force``).  Returns True when a file was
        written."""
        self._latest = (warm, hist, bool(converged), bool(aborted))
        # a boundary commit supersedes any mid-epoch cursor: the carry
        # is exact here, so the next save must NOT claim a partial pass
        self._extra = None
        if self.stream_hook is not None:
            self.stream_hook.on_boundary()
        if not (force or self._due(int(warm.prior_iters))):
            return False
        self._save(*self._latest)
        return True

    def update_stream(self, extra: dict) -> bool:
        """Mid-epoch commit: force-write the last boundary carry PLUS
        the namespaced rider entries (the streaming layer's
        ``stream_*`` cursor) — a preemption after this save resumes
        from the boundary warm state and replays forward to the cursor
        instead of restarting the epoch.  No-op (False) before the
        first boundary state is seen: a cursor without a carry to
        anchor it would be meaningless."""
        if self._latest is None:
            return False
        self._extra = dict(extra)
        self._save(*self._latest, action="checkpoint")
        return True

    def flush(self, *, reason: str = "flush") -> bool:
        """Force-write the latest known state (no-op when none seen)."""
        if self._latest is None:
            return False
        self._save(*self._latest, action=reason)
        return True

    def _save(self, warm, hist, converged, aborted, *,
              action: str = "checkpoint") -> None:
        self._rotate()
        ckpt.save_checkpoint(
            self.path, warm,
            None if hist is None else np.asarray(hist),
            converged=converged, aborted=aborted,
            fingerprint=self.fingerprint, extra=self._extra)
        self._last_saved_iters = int(warm.prior_iters)
        self._last_saved_t = self._clock()
        self.saves += 1
        if self.telemetry is not None:
            self.telemetry.recovery(
                action=action, path=self.path,
                to_iter=int(warm.prior_iters), source="autockpt")

    def _rotate(self) -> None:
        """Shift the retention chain one slot (oldest generation falls
        off); each shift is a rename, so the chain never holds a
        half-copied file."""
        gens = generation_paths(self.path, self.keep)
        if os.path.exists(gens[-1]) and self.keep > 1:
            os.unlink(gens[-1])
        for newer, older in zip(reversed(gens[:-1]), reversed(gens[1:])):
            if os.path.exists(newer) and self.keep > 1:
                os.replace(newer, older)

    # -- corruption-tolerant load -----------------------------------------
    def load(self, template: Any) -> Optional[ckpt.LoadedCheckpoint]:
        """Walk the generation chain newest → oldest; return the first
        loadable checkpoint (fingerprint-validated), skipping corrupt
        generations with a ``recovery`` record each.  None when no
        generation exists/survives — corrupt-only chains resume from
        scratch rather than refusing to run (every skip was
        recorded)."""
        found_any = False
        for gen, path in enumerate(generation_paths(self.path, self.keep)):
            if not os.path.exists(path):
                continue
            found_any = True
            try:
                loaded = ckpt.load_checkpoint(
                    path, template, expect_fingerprint=self.fingerprint,
                    fallback_to_bak=False)
            except ckpt.CheckpointCorruptError as e:
                ckpt.logger.warning("skipping corrupt checkpoint "
                                    "generation %d: %s", gen, e)
                if self.telemetry is not None:
                    self.telemetry.recovery(
                        action="checkpoint_fallback", path=path,
                        generation=gen, reason=str(e), source="autockpt")
                continue
            if loaded is not None:
                if gen > 0 and self.telemetry is not None:
                    self.telemetry.recovery(
                        action="resume", path=path, generation=gen,
                        to_iter=int(loaded.warm.prior_iters),
                        source="autockpt")
                # seed cadence state so the next segment doesn't
                # immediately re-save what we just read
                self._last_saved_iters = int(loaded.warm.prior_iters)
                self._last_saved_t = self._clock()
                self.loaded_extras = dict(
                    getattr(loaded, "extras", None) or {})
                if self.stream_hook is not None and self.loaded_extras:
                    self.stream_hook.adopt(self.loaded_extras)
                return loaded
        if found_any:
            ckpt.logger.warning(
                "every checkpoint generation at %r was corrupt; "
                "starting from scratch", self.path)
        return None

    # -- preemption -------------------------------------------------------
    def _on_signal(self, signum, frame):
        self.preempted = True
        self.flush(reason="preemption_flush")
        raise Preempted(signum)

    def install_signal_handlers(self, signals=(signal_lib.SIGTERM,
                                               signal_lib.SIGINT)):
        """Install the flush-then-``Preempted`` handler (main thread
        only — Python routes signals there).  Idempotent; pair with
        :meth:`uninstall_signal_handlers` (or use the instance as a
        context manager)."""
        if self._prev_handlers is not None:
            return
        self._prev_handlers = {}
        for s in signals:
            self._prev_handlers[s] = signal_lib.signal(s, self._on_signal)

    def uninstall_signal_handlers(self):
        if self._prev_handlers is None:
            return
        for s, h in self._prev_handlers.items():
            signal_lib.signal(s, h)
        self._prev_handlers = None

    def __enter__(self):
        self.install_signal_handlers()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.uninstall_signal_handlers()
        return False
