"""Multi-host resilience: barrier-committed checkpoints, heartbeats,
host-loss detection, and elastic resume onto a changed topology.

The reference's distributed fault tolerance is Spark's: a dead executor
costs its partitions (recomputed from lineage) and ``treeAggregate``
survives because the driver reschedules.  The SPMD port has no driver —
every host runs the same program, and PR 3's resilience layer (retry /
rollback / ``AutoCheckpointer``) is single-process.  This module is the
multi-host completion, in three pieces:

**Commit-barrier checkpointing** (:class:`DistributedCheckpointer`).
Each host atomically writes its own generation-stamped shard file
(``utils.checkpoint.atomic_savez`` — tempfile+rename, per-entry CRC32);
then all hosts exchange ``(generation, file CRC32, size, warm-state
CRC32)`` through one small allgather — which is also the BARRIER: the
exchange returns only once every shard is on disk — and the primary
host alone writes the ``manifest.json`` commit record
(``resilience.manifest``).  A generation without its manifest does not
exist; a manifest whose shards are missing/torn/mixed-generation is
refused and the loader falls back one generation — the multi-host
extension of the single-host ``.bak`` chain.  The exchange additionally
refuses a MIXED-GENERATION commit (two hosts trying to commit different
generations = a partitioned job) and a replica-divergence commit (hosts
disagreeing on the supposedly-replicated warm state).

**Host health** (:class:`HeartbeatWriter` / :class:`HostMonitor`).
Every host atomically rewrites a small ``heartbeat.hNNN.json`` at each
segment boundary and emits a ``heartbeat`` record through the obs event
bus.  A monitor (any process with filesystem access — the surviving
hosts, or an external supervisor) reads staleness from the files and
raises :class:`~spark_agd_tpu.resilience.errors.HostLost` — classified
TRANSIENT by ``errors.classify_failure``: the work is retryable, just
possibly on a smaller topology.

**Elastic resume** (:func:`load_for_topology`).  Resuming on the SAME
process count reads back exactly this host's own shard bytes —
bit-identical by construction.  Resuming on a DIFFERENT count (a host
died; capacity grew back) gathers what was sharded to the host level —
the data-partition assignment and any row-sharded extras — re-splits
them for the new topology (partitions round-robin like
``data.ingest.local_partitions``; rows by ``parallel.multihost.
local_rows_slice``), and takes the replicated ``AGDWarmState`` from the
primary shard (the commit barrier proved all replicas byte-equal).  The
math is unaffected: AGD's carry is replicated, so a 2→1 resume
continues the SAME trajectory on re-assembled data.

Proof harness: ``tools/dist_fault_drill.py`` (SIGKILL one of two real
processes mid-run, elastic resume on one) and
``tests/test_dist_resilience.py``.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..utils import checkpoint as ckpt
from . import manifest as manifest_lib
from .autockpt import AutoCheckpointer
from .errors import HostLost

logger = logging.getLogger("spark_agd_tpu")

# npz entry prefix for row-sharded extras in a shard payload
ROWSTATE_PREFIX = "rowstate::"

_HEARTBEAT_RE = re.compile(r"^heartbeat\.h(\d{3})\.json$")


def _process_defaults(process_index, process_count) -> Tuple[int, int]:
    if process_index is None or process_count is None:
        import jax

        if process_index is None:
            process_index = jax.process_index()
        if process_count is None:
            process_count = jax.process_count()
    if not 0 <= int(process_index) < int(process_count):
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"process_count {process_count}")
    return int(process_index), int(process_count)


def _default_exchange(row: np.ndarray) -> np.ndarray:
    from ..parallel import multihost

    return multihost.process_allgather_int64(row)


def _warm_crc(warm) -> int:
    """CRC32 over the warm state's leaf bytes + scalars — the replica-
    divergence check exchanged at commit (every host's supposedly-
    replicated carry must be byte-equal)."""
    import zlib

    crc = 0
    payload = ckpt.warm_payload(warm)
    for name in sorted(payload):
        if name == "loss_history":
            continue  # histories may legitimately be rank-0-only
        crc = zlib.crc32(np.ascontiguousarray(payload[name]).tobytes(),
                         crc)
    return crc


class LoadedDistCheckpoint(NamedTuple):
    """What :func:`load_for_topology` returns — a superset of
    ``utils.checkpoint.LoadedCheckpoint`` (the supervisor reads the
    first five fields), plus the distributed bookkeeping."""

    warm: Any
    loss_history: np.ndarray
    converged: bool
    aborted: bool
    fingerprint: Optional[str]
    generation: int
    saved_process_count: int
    elastic: bool                       # topology changed on resume
    partitions: Optional[Tuple[str, ...]]  # THIS host's re-split files
    row_state: Dict[str, np.ndarray]    # THIS host's re-split rows
    # namespaced rider entries (``stream_*`` mid-epoch cursor) — same
    # contract as utils.checkpoint.LoadedCheckpoint.extras
    extras: Dict[str, np.ndarray] = {}


def _check_embedded_generation(path: str, entries: Dict[str, np.ndarray],
                               expect: int) -> None:
    if "generation" not in entries:
        raise ckpt.CheckpointCorruptError(
            path, KeyError("shard carries no generation id"))
    got = int(entries["generation"])
    if got != expect:
        raise ckpt.CheckpointCorruptError(
            path, ValueError(
                f"shard embeds generation {got}, manifest says "
                f"{expect} (mixed-generation set refused)"))


def _shard_partitions(entries: Dict[str, np.ndarray]) -> Optional[List[str]]:
    if "partitions" not in entries:
        return None
    return [str(x) for x in np.atleast_1d(entries["partitions"])]


def _shard_row_state(entries: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return {k[len(ROWSTATE_PREFIX):]: entries[k]
            for k in entries if k.startswith(ROWSTATE_PREFIX)}


def reshard_partitions(saved: Sequence[Sequence[str]],
                       process_index: int,
                       process_count: int) -> Tuple[str, ...]:
    """Re-split saved per-host partition assignments for a new topology
    — union, then the SAME sorted round-robin rule as
    ``data.ingest.local_partitions``, so an unchanged topology gets its
    original assignment back and a changed one gets the assignment a
    fresh ingest would compute."""
    union = sorted({p for host in saved for p in host})
    return tuple(union[process_index::process_count])


def load_for_topology(
    directory: str,
    template: Any,
    *,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    fingerprint: Optional[str] = None,
    telemetry=None,
) -> Optional[LoadedDistCheckpoint]:
    """Load the newest VERIFIABLE generation for the given topology —
    see the module docstring.  Walks committed generations newest →
    oldest, refusing (with one ``checkpoint_fallback`` recovery record
    each) any whose manifest is unreadable, whose shard files fail the
    manifest's size/CRC32, whose npz entries fail their per-entry CRCs,
    or whose shards embed a different generation than the manifest.
    Returns None when nothing survives (every refusal was recorded).
    A fingerprint mismatch RAISES ``ValueError`` — that is the wrong
    problem at a reused path, not corruption to fall back from."""
    process_index, process_count = _process_defaults(process_index,
                                                     process_count)
    gens = manifest_lib.committed_generations(directory)
    for gen in gens:
        try:
            m = manifest_lib.load_manifest(directory, gen)
        except (ValueError, OSError) as e:
            _fallback(telemetry, directory, gen, f"manifest unreadable: {e}")
            continue
        problems = manifest_lib.verify_manifest(m, directory)
        if problems:
            _fallback(telemetry, directory, gen, "; ".join(problems))
            continue
        try:
            return _load_generation(directory, m, template,
                                    process_index, process_count,
                                    fingerprint, telemetry)
        except ckpt.CheckpointCorruptError as e:
            _fallback(telemetry, directory, gen, str(e))
            continue
    if gens:
        logger.warning(
            "every committed generation under %r failed verification; "
            "resuming from scratch", directory)
    return None


def _fallback(telemetry, directory: str, generation: int,
              reason: str) -> None:
    logger.warning("refusing checkpoint generation %d under %r: %s",
                   generation, directory, reason)
    if telemetry is not None:
        telemetry.recovery(action="checkpoint_fallback", path=directory,
                           generation=generation, reason=reason,
                           source="dist_ckpt")


def _load_generation(directory, m, template, process_index,
                     process_count, fingerprint, telemetry):
    elastic = (m.process_count != process_count)
    if not elastic:
        # unchanged topology: this host reads back exactly its own
        # shard's bytes — bit-identical resume by construction
        path = m.shard_path(directory, process_index)
        entries = ckpt.read_npz_entries(path)
        _check_embedded_generation(path, entries, m.generation)
        lc = ckpt.checkpoint_from_entries(
            path, ckpt._Entries(path, entries), template, fingerprint)
        return LoadedDistCheckpoint(
            *lc[:5], generation=m.generation,
            saved_process_count=m.process_count, elastic=False,
            partitions=(tuple(p) if (p := _shard_partitions(entries))
                        is not None else None),
            row_state=_shard_row_state(entries), extras=lc.extras)

    # changed topology: gather every host's shard, re-split
    per_host = []
    for s in sorted(m.shards, key=lambda s: s.process):
        path = os.path.join(directory, s.path)
        entries = ckpt.read_npz_entries(path)
        _check_embedded_generation(path, entries, m.generation)
        per_host.append((path, entries))
    path0, e0 = per_host[0]
    # the warm carry is replicated (byte-equality across hosts was
    # verified by the commit exchange): the primary's copy is canonical
    lc = ckpt.checkpoint_from_entries(
        path0, ckpt._Entries(path0, e0), template, fingerprint)

    saved_parts = [p for _, e in per_host
                   if (p := _shard_partitions(e)) is not None]
    partitions = (reshard_partitions(saved_parts, process_index,
                                     process_count)
                  if saved_parts else None)

    from ..parallel import multihost as mh

    names = sorted({k for _, e in per_host
                    for k in e if k.startswith(ROWSTATE_PREFIX)})
    row_state = {}
    for k in names:
        whole = np.concatenate(
            [e[k] for _, e in per_host if k in e], axis=0)
        row_state[k[len(ROWSTATE_PREFIX):]] = whole[
            mh.local_rows_slice(whole.shape[0], process_index,
                                process_count)]

    if telemetry is not None:
        telemetry.recovery(
            action="elastic_resume", path=directory,
            generation=m.generation,
            saved_process_count=m.process_count,
            process_count=process_count, process=process_index,
            to_iter=int(lc.warm.prior_iters), source="dist_ckpt")
    logger.warning(
        "elastic resume: generation %d was saved by %d processes, "
        "resuming as process %d/%d from iteration %d",
        m.generation, m.process_count, process_index, process_count,
        int(lc.warm.prior_iters))
    return LoadedDistCheckpoint(
        *lc[:5], generation=m.generation,
        saved_process_count=m.process_count, elastic=True,
        partitions=partitions, row_state=row_state, extras=lc.extras)


class DistributedCheckpointer(AutoCheckpointer):
    """The multi-host :class:`~spark_agd_tpu.resilience.autockpt.
    AutoCheckpointer`: same cadence knobs (``every_iters`` /
    ``every_seconds``), same supervisor interface (``load`` / ``update``
    / signal handlers), but each save is a barrier-committed GENERATION
    (see module docstring) in ``directory`` instead of a ``.bak`` chain
    at one path, and ``load`` is topology-elastic.

    ``partitions`` (this host's data-partition file list, from
    ``data.ingest.local_partitions``) and ``row_state`` (row-sharded
    per-host arrays) ride in every shard so a resume on a different
    process count can re-assign them.  ``mesh_shape`` is stamped into
    the manifest for post-mortems.

    ``exchange`` (tests/drills) replaces the allgather barrier — it
    receives this host's int64 ``(generation, crc32, size, warm_crc)``
    row and must return the ``(process_count, 4)`` all-host stack only
    after every host has contributed.  The default uses
    ``parallel.multihost.process_allgather_int64`` (gloo on CPU, ICI/DCN
    on pods) and degrades to identity on a single process.

    Caveat shared with every collective checkpoint (orbax included):
    saves are COLLECTIVE.  All hosts must call ``update``/``flush`` the
    same number of times with the same cadence state, or the exchange
    deadlocks — which is why the supervisor only checkpoints at segment
    boundaries, where SPMD hosts are in lockstep, and why the
    preemption flush assumes the signal hit every host (the norm for
    maintenance events)."""

    def __init__(self, directory: str, *,
                 every_iters: Optional[int] = None,
                 every_seconds: Optional[float] = None,
                 keep: int = 2,
                 fingerprint: Optional[str] = None,
                 telemetry=None,
                 mesh_shape: Optional[Dict[str, int]] = None,
                 partitions: Optional[Sequence[str]] = None,
                 row_state: Optional[Dict[str, np.ndarray]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 exchange: Optional[Callable] = None,
                 clock=time.monotonic):
        super().__init__(directory, every_iters=every_iters,
                         every_seconds=every_seconds, keep=keep,
                         fingerprint=fingerprint, telemetry=telemetry,
                         clock=clock)
        self.directory = directory
        self.mesh_shape = dict(mesh_shape) if mesh_shape else None
        self.partitions = (None if partitions is None
                           else [str(p) for p in partitions])
        self.row_state = dict(row_state or {})
        self.process_index, self.process_count = _process_defaults(
            process_index, process_count)
        self._exchange = exchange or _default_exchange
        latest = manifest_lib.committed_generations(directory)
        self._next_generation = (latest[0] + 1) if latest else 0

    # -- save: shard write → barrier → primary commit ---------------------
    def _save(self, warm, hist, converged, aborted, *,
              action: str = "checkpoint") -> None:
        gen = self._next_generation
        payload = ckpt.warm_payload(
            warm, None if hist is None else np.asarray(hist),
            converged=converged, aborted=aborted,
            fingerprint=self.fingerprint, extra=self._extra)
        payload["generation"] = np.asarray(gen)
        payload["process_index"] = np.asarray(self.process_index)
        payload["process_count"] = np.asarray(self.process_count)
        if self.partitions is not None:
            payload["partitions"] = np.asarray(self.partitions)
        for name, arr in self.row_state.items():
            payload[ROWSTATE_PREFIX + name] = np.asarray(arr)
        shard = manifest_lib.shard_name(gen, self.process_index)
        shard_path = os.path.join(self.directory, shard)
        ckpt.atomic_savez(shard_path, payload)

        # the commit barrier rides as one causal ``ckpt_commit`` span
        # (obs.trace) — under the supervisor's run span, so a slow or
        # wedged barrier is visible per host in the timeline
        commit_span = (self.telemetry.trace_span(
            "ckpt_commit", generation=int(gen),
            to_iter=int(warm.prior_iters))
            if self.telemetry is not None else None)
        with commit_span if commit_span is not None \
                else contextlib.nullcontext():
            self._commit(warm, gen, shard_path, converged, aborted,
                         action)

    def _commit(self, warm, gen, shard_path, converged, aborted,
                action):
        row = np.asarray(
            [gen, manifest_lib.crc32_file(shard_path),
             os.path.getsize(shard_path), _warm_crc(warm)], np.int64)
        gathered = self._exchange(row)  # the commit barrier
        gathered = np.asarray(gathered, np.int64).reshape(
            self.process_count, row.size)
        gens = gathered[:, 0]
        if not (gens == gen).all():
            raise RuntimeError(
                "mixed-generation commit refused: hosts are trying to "
                f"commit generations {sorted(set(int(g) for g in gens))} "
                "— the job is out of lockstep; restart from the last "
                "committed manifest")
        warm_crcs = gathered[:, 3]
        if not (warm_crcs == warm_crcs[0]).all():
            raise RuntimeError(
                "replica divergence at checkpoint: the supposedly-"
                "replicated AGDWarmState differs across hosts "
                f"(CRC32s {[hex(int(c)) for c in warm_crcs]}); refusing "
                "to commit a checkpoint that would hide it")

        if self.process_index == 0:
            shards = [manifest_lib.ShardEntry(
                path=manifest_lib.shard_name(gen, p), process=p,
                crc32=int(gathered[p, 1]), size=int(gathered[p, 2]))
                for p in range(self.process_count)]
            manifest_lib.write_manifest(self.directory, manifest_lib.Manifest(
                generation=gen, process_count=self.process_count,
                shards=shards, mesh_shape=self.mesh_shape,
                fingerprint=self.fingerprint, converged=bool(converged),
                aborted=bool(aborted),
                prior_iters=int(warm.prior_iters)))
            manifest_lib.gc_generations(self.directory, self.keep)
        self._next_generation = gen + 1
        self._last_saved_iters = int(warm.prior_iters)
        self._last_saved_t = self._clock()
        self.saves += 1
        if self.telemetry is not None:
            self.telemetry.recovery(
                action=action, path=self.directory, generation=gen,
                to_iter=int(warm.prior_iters),
                process=self.process_index,
                process_count=self.process_count, source="dist_ckpt")

    # -- load: newest verifiable generation, topology-elastic -------------
    def load(self, template: Any) -> Optional[LoadedDistCheckpoint]:
        loaded = load_for_topology(
            self.directory, template,
            process_index=self.process_index,
            process_count=self.process_count,
            fingerprint=self.fingerprint, telemetry=self.telemetry)
        if loaded is not None:
            self._next_generation = loaded.generation + 1
            self._last_saved_iters = int(loaded.warm.prior_iters)
            self._last_saved_t = self._clock()
            self.loaded_extras = dict(loaded.extras or {})
            if self.stream_hook is not None and self.loaded_extras:
                self.stream_hook.adopt(self.loaded_extras)
            if loaded.elastic and loaded.partitions is not None \
                    and self.partitions is None:
                # adopt the re-split assignment so the NEXT generation
                # records the topology we actually resumed onto
                self.partitions = list(loaded.partitions)
            if self.telemetry is not None and not loaded.elastic:
                self.telemetry.recovery(
                    action="resume", path=self.directory,
                    generation=loaded.generation,
                    to_iter=int(loaded.warm.prior_iters),
                    process=self.process_index, source="dist_ckpt")
        return loaded


# ---------------------------------------------------------------------------
# Host health: heartbeat files + the obs event stream, and the monitor
# that turns staleness into HostLost.
# ---------------------------------------------------------------------------


def heartbeat_name(process: int) -> str:
    return f"heartbeat.h{process:03d}.json"


class HeartbeatWriter:
    """One host's liveness beacon: :meth:`beat` atomically rewrites
    ``heartbeat.hNNN.json`` in ``directory`` (tiny: timestamp, pid,
    iteration, phase) and emits a ``heartbeat`` record through the
    telemetry bus when one is attached.  Call it at segment boundaries
    (the supervisor does, via ``heartbeat=``) — often enough for a
    monitor to notice death within a segment, cheap enough to never
    show up in a profile."""

    def __init__(self, directory: str, *,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 telemetry=None, clock=time.time):
        self.directory = directory
        self.process_index, self.process_count = _process_defaults(
            process_index, process_count)
        self.telemetry = telemetry
        self._clock = clock
        self.beats = 0

    @property
    def path(self) -> str:
        return os.path.join(self.directory,
                            heartbeat_name(self.process_index))

    def beat(self, *, iter: Optional[int] = None,
             phase: Optional[str] = None) -> dict:
        rec = {"process": self.process_index,
               "process_count": self.process_count,
               "pid": os.getpid(), "time": round(self._clock(), 3)}
        if iter is not None:
            rec["iter"] = int(iter)
        if phase is not None:
            rec["phase"] = str(phase)
        manifest_lib._atomic_write_text(self.path, json.dumps(rec))
        self.beats += 1
        if self.telemetry is not None:
            fields = {k: rec[k] for k in ("process_count", "pid",
                                          "iter", "phase") if k in rec}
            self.telemetry.heartbeat(process=self.process_index,
                                     **fields)
        return rec


class HostMonitor:
    """Reads the heartbeat files and turns staleness into
    :class:`~spark_agd_tpu.resilience.errors.HostLost`.

    A host counts as LOST when it has beaten at least once and its file
    is older than ``stale_after_s``; a host that never appeared is
    "unseen" (still starting — not a loss).  ``expected`` (process
    indices) scopes the check; default: whatever files exist.  Usable
    from any process that sees the directory: a surviving peer (pass
    ``monitor=`` to the supervisor) or an external babysitter (the
    drill's parent process).

    SLOW is a distinct verdict from LOST (:meth:`verdicts`): a host
    whose latest beat carries ``phase="slow"`` (the chaos sub-interval
    beats during an injected sleep — ``ChaosSchedule`` keeps beating
    through sleeps once the supervisor binds the heartbeat), or whose
    beat age sits between ``slow_after_s`` and ``stale_after_s``, is
    degraded-but-alive.  Only LOST raises; a slow host is the
    straggler scheduler's problem (``resilience.scheduler``), not the
    host-loss path's — before this split, a ``slow_host`` sleep longer
    than the staleness window was misdiagnosed as a dead host."""

    def __init__(self, directory: str, *, stale_after_s: float = 30.0,
                 slow_after_s: Optional[float] = None,
                 expected: Optional[Sequence[int]] = None,
                 telemetry=None, clock=time.time):
        if stale_after_s <= 0:
            raise ValueError("stale_after_s must be > 0")
        if slow_after_s is not None and not \
                0 < slow_after_s < stale_after_s:
            raise ValueError("slow_after_s must sit in "
                             "(0, stale_after_s)")
        self.directory = directory
        self.stale_after_s = float(stale_after_s)
        self.slow_after_s = (None if slow_after_s is None
                             else float(slow_after_s))
        self.expected = None if expected is None else sorted(
            int(p) for p in expected)
        self.telemetry = telemetry
        self._clock = clock
        self._reported: set = set()

    def poll(self) -> Dict[int, dict]:
        """Per-host last-known beat (the parsed file + ``age_s``)."""
        out: Dict[int, dict] = {}
        if not os.path.isdir(self.directory):
            return out
        now = self._clock()
        for name in sorted(os.listdir(self.directory)):
            m = _HEARTBEAT_RE.match(name)
            if not m:
                continue
            p = int(m.group(1))
            if self.expected is not None and p not in self.expected:
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    rec = json.load(f)
            except (ValueError, OSError):
                continue  # mid-rewrite / garbage: treat as unseen
            rec["age_s"] = max(0.0, now - float(rec.get("time", 0.0)))
            out[p] = rec
        return out

    def lost_hosts(self) -> List[int]:
        return [p for p, rec in self.poll().items()
                if rec["age_s"] > self.stale_after_s]

    def verdicts(self) -> Dict[int, str]:
        """Per-host ``"ok"`` | ``"slow"`` | ``"lost"`` — see the class
        docstring.  SLOW means alive-but-degraded: the latest beat
        says ``phase="slow"`` (an injected or self-reported degraded
        stretch) or the beat age exceeds ``slow_after_s`` without
        crossing the staleness line."""
        out: Dict[int, str] = {}
        for p, rec in sorted(self.poll().items()):
            age = rec["age_s"]
            if age > self.stale_after_s:
                out[p] = "lost"
            elif rec.get("phase") == "slow" or (
                    self.slow_after_s is not None
                    and age > self.slow_after_s):
                out[p] = "slow"
            else:
                out[p] = "ok"
        return out

    def slow_hosts(self) -> List[int]:
        return [p for p, v in self.verdicts().items() if v == "slow"]

    def check(self) -> None:
        """Raise :class:`HostLost` for the first newly-stale host (one
        ``host_lost`` recovery record per host per monitor, so a retry
        loop does not spam the stream)."""
        for p, rec in sorted(self.poll().items()):
            if rec["age_s"] <= self.stale_after_s:
                continue
            if self.telemetry is not None and p not in self._reported:
                self.telemetry.recovery(
                    action="host_lost", process=p,
                    reason=f"no heartbeat for {rec['age_s']:.1f}s "
                           f"(last at iter {rec.get('iter')})",
                    source="host_monitor")
            self._reported.add(p)
            raise HostLost(p, stale_for_s=rec["age_s"])
