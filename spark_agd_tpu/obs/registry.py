"""Metrics registry: counters, gauges, and span timers.

The reference has NO metrics surface of its own — it computes L, theta,
step size, and restart decisions every iteration and discards them all
(reference ``AcceleratedGradientDescent.scala:302-335``; SURVEY §5),
leaning on the Spark UI for anything operational.  The ROADMAP's
production north-star needs first-class metrics: "Understanding and
Optimizing the Performance of Distributed ML Applications on Apache
Spark" (PAPERS.md) shows per-phase timing breakdowns (compute vs.
aggregate vs. overhead) are what drives distributed-optimizer tuning.

This module is the passive half of the telemetry subsystem: named
counters/gauges/span-timer instruments that any layer can write to
cheaply (a dict update under a lock — no I/O), snapshotted on demand.
The active half (events streamed to sinks while a run executes) lives in
``obs.events`` / ``obs.stream``.

Thread-safe: the benches time runs from watchdog threads, and
``jax.debug.callback`` host callbacks may run on a runtime thread.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class Counter:
    """Monotonic count (cache hits, records emitted, restarts)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-written value (cache dir size, rows staged, device count)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = None
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        return self._value


class SpanTimer:
    """Named wall-clock span, used as a context manager::

        with registry.span("compile"):
            lowered.compile()

    Every completed span appends to ``times`` (seconds) and, when the
    registry has an ``on_span`` hook attached (``obs.Telemetry`` wires
    the event bus there), emits one span event as it closes — so phase
    timings stream out live instead of only existing in the end-of-run
    snapshot.
    """

    __slots__ = ("name", "times", "_lock", "_on_span", "_t0")

    def __init__(self, name: str,
                 on_span: Optional[Callable[[str, float], None]] = None):
        self.name = name
        self.times: List[float] = []
        self._lock = threading.Lock()
        self._on_span = on_span

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        with self._lock:
            self.times.append(dt)
        if self._on_span is not None:
            self._on_span(self.name, dt)
        return False

    @property
    def count(self) -> int:
        return len(self.times)

    @property
    def total(self) -> float:
        return sum(self.times)

    @property
    def last(self) -> Optional[float]:
        return self.times[-1] if self.times else None


class MetricsRegistry:
    """Create-on-first-use instrument registry.

    ``counter(name)`` / ``gauge(name)`` / ``span(name)`` return the same
    instrument for the same name; ``snapshot()`` renders everything as
    one flat dict (span timers as ``{name}.count/.total_s/.last_s``),
    suitable for logging or stamping into a run record.
    """

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._spans: Dict[str, SpanTimer] = {}
        self._lock = threading.Lock()
        self._on_span: Optional[Callable[[str, float], None]] = None

    def set_span_hook(self, fn: Optional[Callable[[str, float], None]]):
        """Called ``fn(name, seconds)`` as each span closes (existing
        span timers are rewired too)."""
        with self._lock:
            self._on_span = fn
            for sp in self._spans.values():
                sp._on_span = fn

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name)
            return self._gauges[name]

    def span(self, name: str) -> SpanTimer:
        with self._lock:
            if name not in self._spans:
                self._spans[name] = SpanTimer(name, self._on_span)
            return self._spans[name]

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for n, c in self._counters.items():
                out[n] = c.value
            for n, g in self._gauges.items():
                out[n] = g.value
            for n, s in self._spans.items():
                out[f"{n}.count"] = s.count
                out[f"{n}.total_s"] = round(s.total, 6)
                if s.last is not None:
                    out[f"{n}.last_s"] = round(s.last, 6)
            return out


# One process-wide default registry: instrumentation sites that have no
# Telemetry object threaded to them (the compile cache's once-per-process
# census, ad-hoc profiling) still land somewhere inspectable.
_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default
