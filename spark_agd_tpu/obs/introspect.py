"""Compiled-program introspection: what did XLA actually build?

PR 1 made *runtime* behaviour observable (live iteration streams, span
timers, one record schema).  This module makes the *compiled program
itself* first-class observability data — the facts every perf PR is
judged by, which until now lived only as ad-hoc assertions in
``tests/test_hlo_cost_shape.py``:

- **FLOP / bytes-accessed estimates** from XLA's cost model
  (``jax.stages.Compiled.cost_analysis()``);
- **HBM footprint** — argument / output / temp / generated-code sizes
  from ``memory_analysis()``, plus a derived peak;
- **collective census** — all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all counts straight from the optimized
  HLO text (the public home of the op-counting helper the HLO guard
  tests pioneered).

Everything lands in one :class:`ProgramCost` record, serializable as
the ``program_cost`` kind of the canonical ``obs.schema`` — so a
run-record JSONL can carry the compiled program's cost model next to
its wall-clock numbers, and ``obs.perfgate`` can gate on *both* (the
MLPerf-on-TPU-pod lesson: regression tracking must be tied to the
compiled program, not just wall clock).

Entry points, by what you hold:

- ``analyze_runner(fit, w0)`` — an ``api.make_runner`` /
  ``api.make_lbfgs_runner`` fit (uses its ``lower_step`` AOT hook);
- ``analyze(fn, *args)`` — any jittable callable (a ``dist_smooth``
  smooth, a ``feature_sharded`` eval, …): jits, lowers, compiles,
  without executing;
- ``analyze_lowered(lowered)`` / ``analyze_compiled(compiled)`` — the
  ``jax.stages`` objects themselves (e.g. ``parallel.grid``'s
  ``fit.lower`` hook).

CPU-deterministic: the XLA CPU backend reports the same cost-analysis
families, so all of this unit-tests without hardware.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

# The ops the census counts — every cross-device collective XLA emits
# for the programs in this repo (the HLO guard tests' union, made the
# one public source of truth).
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

# host round-trip ops — the fused design's forbidden list
# (tests/test_hlo_cost_shape.py::test_no_host_transfers_in_loop)
HOST_TRANSFER_OPS = ("outfeed", "infeed", "send", "recv")


def count_ops(hlo: str, name: str) -> int:
    """Occurrences of HLO op ``name`` in optimized-HLO text (async
    ``-start`` forms counted once, ``-done`` ignored)."""
    return sum(1 for line in hlo.splitlines()
               if f" {name}(" in line or f" {name}-start(" in line)


def collective_census(hlo: str) -> Dict[str, int]:
    """Per-collective op counts for one program's HLO text."""
    return {op: count_ops(hlo, op) for op in COLLECTIVE_OPS}


def _shape_bytes(type_text: str) -> int:
    """Total bytes of every array shape in an HLO result-type string
    (handles tuples: each ``dtype[dims]`` element is summed)."""
    import re

    total = 0
    for m in re.finditer(r"(pred|[a-z]+\d+\w*)\[([\d,]*)\]", type_text):
        dt, dims = m.groups()
        if dt == "pred":
            nbytes = 1
        else:
            bits = int(re.match(r"[a-z]+(\d+)", dt).group(1))
            nbytes = max(1, bits // 8)
        size = 1
        if dims:
            for d in dims.split(","):
                size *= int(d)
        total += size * nbytes
    return total


def collective_bytes(hlo: str) -> Dict[str, int]:
    """Per-collective RESULT bytes summed over one program's HLO text.

    The census above counts *instructions*; this weighs them — the
    number that distinguishes a full-D gradient all-reduce from the
    scalar control-plane psums the sharded update mode leaves behind
    (its all-reduce COUNT goes up — one psum per control scalar — while
    its all-reduce BYTES collapse to a few scalars per iteration; the
    full-D traffic moves to reduce-scatter + all-gather).  Bytes are the
    op's result shape(s): for reduce-scatter that is the post-scatter
    1/N shard, for all-gather the gathered full array — i.e. what the op
    delivers, not what crosses each link (a ring moves ~the same bytes
    for either phrasing).  Async ``-start`` forms count their (operand,
    result) tuple and may overstate; the CPU backend the contract tests
    pin against emits the sync forms."""
    out = {op: 0 for op in COLLECTIVE_OPS}
    for line in hlo.splitlines():
        if " = " not in line:
            continue
        rest = line.split(" = ", 1)[1]
        for op in COLLECTIVE_OPS:
            idx = rest.find(f" {op}(")
            if idx < 0:
                idx = rest.find(f" {op}-start(")
            if idx < 0:
                continue
            out[op] += _shape_bytes(rest[:idx + 1])
            break
    return out


def hlo_text(fn: Callable, *args) -> str:
    """Optimized HLO of ``fn(*args)`` — lowered and compiled, never
    executed.  ``fn`` may already be jitted (anything with ``.lower``)."""
    import jax

    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    return fn.lower(*args).compile().as_text()


@dataclasses.dataclass(frozen=True)
class ProgramCost:
    """One compiled program's cost/memory/collective accounting.

    ``None`` fields mean the backend did not report that family (e.g. a
    backend without a cost model); the collective census always exists
    because it comes from the HLO text itself.  ``peak_hbm_bytes`` is
    the backend's peak when reported, else the argument+output+temp
    sum — an upper bound on live HBM, the quantity the one-chip-scale
    decisions in ``benchmarks/run.py`` are sized against."""

    label: str
    backend: str
    flops: Optional[float]
    transcendentals: Optional[float]
    bytes_accessed: Optional[float]
    argument_bytes: Optional[int]
    output_bytes: Optional[int]
    temp_bytes: Optional[int]
    alias_bytes: Optional[int]
    generated_code_bytes: Optional[int]
    peak_hbm_bytes: Optional[int]
    collectives: Dict[str, int]
    hlo_bytes: int
    # per-collective result bytes (see collective_bytes); defaulted so
    # hand-built ProgramCost literals in older tests stay valid
    collective_bytes: Optional[Dict[str, int]] = None

    @property
    def n_collectives(self) -> int:
        return sum(self.collectives.values())

    def record(self, run_id: str, **fields) -> dict:
        """This cost as a canonical ``program_cost`` record."""
        from . import schema

        return schema.program_cost_record(
            run_id, self.label, self.collectives,
            backend=self.backend, flops=self.flops,
            transcendentals=self.transcendentals,
            bytes_accessed=self.bytes_accessed,
            argument_bytes=self.argument_bytes,
            output_bytes=self.output_bytes,
            temp_bytes=self.temp_bytes,
            alias_bytes=self.alias_bytes,
            generated_code_bytes=self.generated_code_bytes,
            peak_hbm_bytes=self.peak_hbm_bytes,
            hlo_bytes=self.hlo_bytes,
            collective_bytes=self.collective_bytes, **fields)


def _cost_dict(compiled) -> dict:
    """Flatten ``cost_analysis()``'s version-dependent shapes (dict,
    list-of-dict, or None/raise on cost-model-less backends) to one
    dict."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — no cost model on this backend
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def _opt_int(v) -> Optional[int]:
    return None if v is None else int(v)


def analyze_compiled(compiled, label: str = "program") -> ProgramCost:
    """:class:`ProgramCost` of a ``jax.stages.Compiled``."""
    hlo = compiled.as_text()
    cost = _cost_dict(compiled)
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — analysis is optional per backend
        mem = None

    def ga(name):
        return _opt_int(getattr(mem, name, None)) if mem is not None \
            else None

    arg_b = ga("argument_size_in_bytes")
    out_b = ga("output_size_in_bytes")
    tmp_b = ga("temp_size_in_bytes")
    gen_b = ga("generated_code_size_in_bytes")
    peak = ga("peak_memory_in_bytes")
    if peak is None and None not in (arg_b, out_b, tmp_b):
        peak = arg_b + out_b + tmp_b
    try:
        backend = compiled.runtime_executable().platform
    except Exception:  # noqa: BLE001
        import jax

        backend = jax.default_backend()
    flops = cost.get("flops")
    return ProgramCost(
        label=label, backend=str(backend),
        flops=None if flops is None else float(flops),
        transcendentals=(None if cost.get("transcendentals") is None
                         else float(cost["transcendentals"])),
        bytes_accessed=(None if cost.get("bytes accessed") is None
                        else float(cost["bytes accessed"])),
        argument_bytes=arg_b, output_bytes=out_b, temp_bytes=tmp_b,
        alias_bytes=ga("alias_size_in_bytes"),
        generated_code_bytes=gen_b, peak_hbm_bytes=peak,
        collectives=collective_census(hlo), hlo_bytes=len(hlo),
        collective_bytes=collective_bytes(hlo))


def analyze_lowered(lowered, label: str = "program") -> ProgramCost:
    """Compile a ``jax.stages.Lowered`` and analyze it."""
    return analyze_compiled(lowered.compile(), label=label)


def analyze(fn: Callable, *args, label: Optional[str] = None
            ) -> ProgramCost:
    """Lower+compile ``fn(*args)`` (never executed) and analyze the
    program.  ``fn`` may already be jitted."""
    import jax

    if label is None:
        label = getattr(fn, "__name__", "program")
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    return analyze_lowered(fn.lower(*args), label=label)


def analyze_runner(fit: Any, w0, label: Optional[str] = None
                   ) -> ProgramCost:
    """Census of the ONE program an ``api.make_runner`` /
    ``api.make_lbfgs_runner`` fit executes, via its ``lower_step`` AOT
    hook — the same program ``fit(w0)`` runs, so the numbers are the
    runner's, not a parallel reimplementation's."""
    lower = getattr(fit, "lower_step", None)
    if lower is None:
        raise TypeError(
            "fit has no lower_step AOT hook; pass an api.make_runner / "
            "api.make_lbfgs_runner fit, or use introspect.analyze(fn, "
            "*args) on the callable directly")
    if label is None:
        label = getattr(fit, "algorithm", "agd")
    return analyze_lowered(lower(w0), label=label)


def _backend_initialized() -> bool:
    """Whether a jax backend already exists (so ``jax.devices()`` is a
    cache read, not an instantiation that could hang on a wedged
    accelerator tunnel — the AVAILABILITY.md failure mode the bench
    watchdog exists for)."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001 — private surface moved; can't
        # tell, so let the caller proceed normally
        return True


def environment_fingerprint(mesh=None, *,
                            only_if_initialized: bool = False) -> dict:
    """The run-record environment-provenance fields: jax/jaxlib
    versions, backend, device kind/count, process count, (given a
    ``Mesh``) the mesh shape — what ``obs.perfgate`` refuses to compare
    across — plus the hardened host half (``obs.scaling.
    host_fingerprint``): cpu count, 1-minute loadavg, cpufreq governor
    and turbo state, and the container-cgroup CPU quota.  The host
    fields need no backend, so ``bench.py``'s degraded paths stamp them
    too; the BENCH_r01–r05 contamination story is exactly the drift
    these fields make visible.

    Touches the backend (``jax.devices()``) — unless
    ``only_if_initialized=True`` and no backend exists yet, in which
    case only the version + host fields are returned (the bench
    watchdog's error path must never block on instantiating a wedged
    backend)."""
    import jax

    from . import scaling as _scaling

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "unknown")
    except ImportError:  # pragma: no cover — jax implies jaxlib
        jaxlib_version = "unknown"
    out = {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib_version,
    }
    out.update(_scaling.host_fingerprint())
    if only_if_initialized and not _backend_initialized():
        return out
    devs = jax.devices()
    out.update({
        "platform": devs[0].platform,
        "device_kind": devs[0].device_kind,
        "n_devices": len(devs),
        "n_processes": jax.process_count(),
    })
    if mesh is not None:
        out["mesh_shape"] = {str(k): int(v)
                             for k, v in dict(mesh.shape).items()}
    return out
