"""``Telemetry``: the one object threaded through ``api.run`` /
``api.make_runner`` (and the L-BFGS runners) as ``telemetry=``.

Bundles the three telemetry primitives:

- a :class:`~spark_agd_tpu.obs.registry.MetricsRegistry` (counters,
  gauges, span timers) — the passive accumulator;
- an :class:`~spark_agd_tpu.obs.events.EventBus` over pluggable sinks —
  the active stream.  Spans emit one ``span`` record as they close;
- the **live in-loop iteration stream**: :meth:`iteration_callback`
  returns the host function ``core.agd`` / ``core.lbfgs`` invoke via
  ``jax.debug.callback`` from INSIDE the compiled ``lax.while_loop`` —
  per-iteration records (iter, loss, L, theta, step, restarted) arrive
  while the program runs, not after ``block_until_ready``.

**Overhead caveat**: the callback adds a host round-trip per iteration
(an outfeed on TPU), which is exactly the traffic the fused design
removed — so telemetry is strictly opt-in (``telemetry=None`` compiles
the identical program as before, no callback in the HLO) and tier-1 /
benchmark timings are unaffected by this subsystem existing.  Enable it
for debugging convergence, watching long production fits, or feeding
dashboards; disable it when timing.  ``every=N`` thins the emitted
stream N:1 host-side (the callback still fires per iteration — thinning
bounds sink I/O, not the round-trip).
"""

from __future__ import annotations

import math
import re
import time as _time
from typing import Iterable, List, Optional

from . import schema, trace as trace_lib
from .events import EventBus
from .flight import FlightRecorder
from .registry import MetricsRegistry
from .sinks import InMemorySink, Sink


# callback kwarg -> canonical record field (the cores pass their
# internal names; records use the schema's)
_FIELD_NAMES = {"big_l": "L"}


def _scalar(v):
    """Host-side normalize one callback value (np scalar -> python)."""
    try:
        v = v.item()
    except AttributeError:
        pass
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return v
    return float(v)


class Telemetry:
    """See module docstring.  With no ``sinks`` argument an in-memory
    sink is created so :attr:`records` / :meth:`iterations` work out of
    the box; pass explicit sinks (``JSONLSink``, ``CSVSink``,
    ``LoggingSink``, ``TensorBoardSink``) to stream elsewhere.

    ``host_mode``: ``"all"`` (default; single-host no-op) or
    ``"primary"`` (rank-0-only emission on multihost jobs) — see
    ``obs.events.EventBus``.

    ``profile_dir``: when set, the FIRST instrumented ``execute`` phase
    of a ``telemetry=`` fit is captured as a JAX profiler trace into
    this directory (``utils.profiling`` one-shot capture), with every
    span phase wrapped in a matching ``TraceAnnotation`` so the span
    timers and the device timeline line up.  One-shot by design:
    traces are large and ``start_trace`` cannot nest.

    ``flight``: the always-on crash flight recorder (``obs.flight``) —
    a bounded in-memory ring of the last N records, dumped by failure
    paths so every ``SupervisorGivingUp`` / ``QuorumLost`` /
    ``ServeOverloaded`` ships with its last-seconds timeline.  True
    (default) attaches a fresh :class:`~spark_agd_tpu.obs.flight.
    FlightRecorder`; pass a configured recorder, or ``False`` to opt
    out.  ``flight_dir`` is where automatic failure dumps land —
    without it the ring exists but ``dump_on_failure`` writes nothing
    (no surprise files).
    """

    def __init__(self, sinks: Optional[Iterable[Sink]] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 every: int = 1, host_mode: str = "all",
                 run_id: Optional[str] = None,
                 profile_dir: Optional[str] = None,
                 flight=True, flight_dir: Optional[str] = None):
        self.run_id = run_id or schema.new_run_id()
        self.profile_dir = profile_dir
        self.registry = registry or MetricsRegistry()
        self._mem: Optional[InMemorySink] = None
        if sinks is None:
            self._mem = InMemorySink()
            sinks = [self._mem]
        else:
            sinks = list(sinks)
            for s in sinks:
                if isinstance(s, InMemorySink):
                    self._mem = s
                    break
        self.flight: Optional[FlightRecorder] = None
        for s in sinks:
            if isinstance(s, FlightRecorder):
                self.flight = s
                break
        if self.flight is None and flight:
            self.flight = (flight if isinstance(flight, FlightRecorder)
                           else FlightRecorder(directory=flight_dir))
            sinks = list(sinks) + [self.flight]
        if self.flight is not None and flight_dir is not None \
                and self.flight.directory is None:
            self.flight.directory = flight_dir
        self.bus = EventBus(sinks, host_mode=host_mode)
        self.every = max(1, int(every))
        self.registry.set_span_hook(self._on_span)

    # -- spans ------------------------------------------------------------
    def _on_span(self, name: str, seconds: float) -> None:
        self.bus.emit(schema.span_record(self.run_id, name, seconds))

    def span(self, name: str):
        """Context manager timing a phase; the duration lands in the
        registry AND streams one ``span`` record as it closes."""
        return self.registry.span(name)

    # -- causal tracing (obs.trace / obs.timeline) -------------------------
    def trace_span(self, name: str, *, parent=None, **fields):
        """Context manager opening one CAUSAL span (``obs.trace``):
        parented to the current thread's context (or the explicit
        ``parent`` :class:`~spark_agd_tpu.obs.trace.SpanContext`),
        installed as current for the body, emitted as an ``open``
        record immediately (flushed — a killed host leaves a truncated
        span on disk) and a closing ``span`` record with the measured
        duration, trace ids, rank, and ``fields``.  ``__enter__``
        returns the span's context; the handle's ``note(**fields)``
        adds outcome fields to the closing record."""
        return trace_lib.TracedSpan(self, name, parent, fields)

    def trace_point(self, name: str, *, seconds: float, ctx=None,
                    parent=None, status: str = "ok",
                    t_start_unix: Optional[float] = None,
                    **fields) -> dict:
        """Emit (and return) one already-measured CLOSED span record —
        the non-context-manager member for latencies measured
        elsewhere (the serve queue's per-request spans).  ``ctx`` is
        the span's own context when pre-allocated; otherwise a fresh
        child of ``parent`` (or of the current context) is minted."""
        if ctx is None:
            ctx = trace_lib.child_of(
                parent if parent is not None
                else trace_lib.current_context())
        rec = schema.span_record(self.run_id, name, float(seconds))
        rec.update(trace_id=ctx.trace_id, span_id=ctx.span_id,
                   parent_id=ctx.parent_id, process=int(ctx.process),
                   status=str(status))
        if t_start_unix is not None:
            rec["t_start_unix"] = round(float(t_start_unix), 6)
        rec.update(fields)
        self.registry.counter("trace.spans").inc()
        self.bus.emit(rec)
        return rec

    def trace_summary(self, *, trace_id: str, spans: int,
                      **fields) -> dict:
        """Emit (and return) a ``trace_summary`` record — one trace's
        analysis rollup (``obs.timeline.analyze(...).summary_fields()``)
        — mirroring the straggler score into the
        ``trace.straggler_score`` gauge so skew rides the run
        summary's metrics snapshot."""
        score = fields.get("straggler_score")
        if isinstance(score, (int, float)) and not isinstance(score,
                                                              bool):
            self.registry.gauge("trace.straggler_score").set(score)
        rec = schema.trace_summary_record(self.run_id, trace_id,
                                          spans, **fields)
        self.bus.emit(rec)
        return rec

    # -- the live in-loop stream ------------------------------------------
    def iteration_callback(self, algorithm: str = "agd"):
        """The host function the fused loops call via
        ``jax.debug.callback`` — one call per executed iteration, kwargs
        are the per-iteration scalars.  ``accepted=False`` calls (an
        L-BFGS iteration whose line search failed — not an executed
        iteration) are counted but not emitted, preserving the
        one-record-per-iteration contract."""
        emitted = self.registry.counter(f"{algorithm}.iterations")
        rejected = self.registry.counter(f"{algorithm}.rejected_steps")
        every = self.every
        run_id = self.run_id
        bus = self.bus
        nonfinite_seen = []  # one numerics_failure per run, not per iter

        def on_iteration(**fields):
            accepted = fields.pop("accepted", None)
            if accepted is not None and not bool(accepted):
                rejected.inc()
                return
            it = int(fields.pop("it"))
            emitted.inc()
            vals = {_FIELD_NAMES.get(k, k): _scalar(v)
                    for k, v in fields.items()}
            loss = vals.get("loss")
            if (not nonfinite_seen and isinstance(loss, float)
                    and not math.isfinite(loss)):
                # the in-loop sanitizer's cheap twin: the streamed loss
                # went non-finite — land the failure in the same JSONL
                # as the metrics instead of only aborting the loop
                nonfinite_seen.append(it)
                self.numerics_failure(
                    f"{algorithm}: non-finite loss in compiled loop",
                    iter=it, algorithm=algorithm, source="iteration")
            if every > 1 and it % every:
                return
            bus.emit(schema.iteration_record(run_id, algorithm, it,
                                             **vals))

        return on_iteration

    # -- records ----------------------------------------------------------
    def emit(self, record: dict) -> None:
        self.bus.emit(record)

    def program_cost(self, cost, **fields) -> dict:
        """Emit (and return) a ``program_cost`` record for one compiled
        program — ``cost`` is an ``obs.introspect.ProgramCost``.  The
        headline numbers also land as registry gauges
        (``program.<label>.flops`` / ``.peak_hbm_bytes`` /
        ``.collectives``) so they ride every ``run_summary`` snapshot."""
        rec = cost.record(self.run_id, **fields)
        for g, v in (("flops", cost.flops),
                     ("peak_hbm_bytes", cost.peak_hbm_bytes),
                     ("collectives", cost.n_collectives)):
            if v is not None:
                self.registry.gauge(f"program.{cost.label}.{g}").set(v)
        self.bus.emit(rec)
        return rec

    def numerics_failure(self, message: str, *, leaf=None,
                         **fields) -> dict:
        """Emit (and return) a ``numerics_failure`` record — a
        sanitizer hit (``utils.debug``) or an in-loop non-finite loss —
        and count it (``numerics.failures``), so the failure lands in
        the same JSONL as the metrics it poisoned."""
        if leaf is None:
            # checkify messages name the failing quantity; surface it
            # as a first-class field when present
            m = re.search(r"leaf (.+?) non-finite", message)
            leaf = m.group(1) if m else None
        self.registry.counter("numerics.failures").inc()
        rec = schema.numerics_failure_record(
            self.run_id, str(message),
            **({"leaf": leaf} if leaf is not None else {}), **fields)
        self.bus.emit(rec)
        return rec

    def attempt(self, *, attempt: int, outcome: str, **fields) -> dict:
        """Emit (and return) an ``attempt`` record — one supervised fit
        attempt (``resilience.supervisor``) — and count it
        (``resilience.attempts``; failures also land in
        ``resilience.failed_attempts``)."""
        self.registry.counter("resilience.attempts").inc()
        if outcome != "ok":
            self.registry.counter("resilience.failed_attempts").inc()
        rec = schema.attempt_record(self.run_id, attempt, outcome,
                                    **fields)
        self.bus.emit(rec)
        return rec

    def heartbeat(self, *, process: Optional[int] = None,
                  **fields) -> dict:
        """Emit (and return) a ``heartbeat`` record — one liveness beat
        of this SPMD process (``resilience.distributed``) — and count it
        (``resilience.heartbeats``).  ``process`` defaults to this
        process's jax index (0 when no backend is up)."""
        if process is None:
            try:
                import jax

                process = jax.process_index()
            except Exception:  # noqa: BLE001 — no backend: single host
                process = 0
        self.registry.counter("resilience.heartbeats").inc()
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.heartbeat_record(self.run_id, int(process), **fields)
        self.bus.emit(rec)
        return rec

    def chaos(self, *, fault: str, **fields) -> dict:
        """Emit (and return) a ``chaos`` record — one injected fault of
        a chaos campaign (``resilience.chaos``) — counted per kind
        (``chaos.<fault>``) so the campaign census rides the metrics
        snapshot."""
        self.registry.counter(f"chaos.{fault}").inc()
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.chaos_record(self.run_id, fault, **fields)
        self.bus.emit(rec)
        return rec

    def journal_replay(self, *, records: int, **fields) -> dict:
        """Emit (and return) a ``journal_replay`` record — one
        recovery-journal replay/repair (``resilience.journal``) — and
        count it (``resilience.journal_replays``)."""
        self.registry.counter("resilience.journal_replays").inc()
        rec = schema.journal_replay_record(self.run_id, records,
                                           **fields)
        self.bus.emit(rec)
        return rec

    def degraded(self, *, surviving: int, **fields) -> dict:
        """Emit (and return) a ``degraded`` record — one quorum-gated
        degraded continuation (``resilience.degrade``) — and count it
        (``resilience.degraded``), so a degraded tail is visible in
        every run summary."""
        self.registry.counter("resilience.degraded").inc()
        rec = schema.degraded_record(self.run_id, surviving, **fields)
        self.bus.emit(rec)
        return rec

    def recovery(self, *, action: str, **fields) -> dict:
        """Emit (and return) a ``recovery`` record — one resilience
        action (retry / rollback / preemption_flush / checkpoint /
        checkpoint_fallback / resume) — counted per action
        (``resilience.<action>``), so the run summary's metrics
        snapshot carries the recovery census."""
        self.registry.counter(f"resilience.{action}").inc()
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.recovery_record(self.run_id, action, **fields)
        self.bus.emit(rec)
        return rec

    def metrics_snapshot(self, *, tool: Optional[str] = None,
                         **extra_metrics) -> dict:
        """Emit (and return) a ``metrics`` record carrying the current
        registry snapshot (plus any ``extra_metrics``) — a mid-run
        checkpoint of the counters/gauges, where ``run_summary``
        attaches the FINAL snapshot at end of run."""
        metrics = self.registry.snapshot()
        metrics.update(extra_metrics)
        rec = schema.metrics_record(self.run_id, metrics, tool=tool)
        self.bus.emit(rec)
        return rec

    def contract_pin(self, *, contract: str, ok: bool,
                     **fields) -> dict:
        """Emit (and return) a ``contract_pin`` record — one
        compiled-program contract check (``analysis.contracts``:
        constant-bytes / donation / collective-census) — counting
        failures (``contracts.violations``), so a pin broken mid-run
        surfaces in the run summary."""
        if not ok:
            self.registry.counter("contracts.violations").inc()
        rec = schema.contract_pin_record(self.run_id, contract, ok,
                                         **fields)
        self.bus.emit(rec)
        return rec

    def serve_request(self, *, rows: int, **fields) -> dict:
        """Emit (and return) a ``serve_request`` record — one inference
        request through the serving plane (``serve.queue``) — counting
        requests and rows (``serve.requests`` / ``serve.rows``), with
        non-ok statuses additionally landing in ``serve.rejected`` /
        ``serve.errors`` so shed load is visible in every run
        summary."""
        self.registry.counter("serve.requests").inc()
        self.registry.counter("serve.rows").inc(int(rows))
        status = fields.get("status")
        if status == "rejected":
            self.registry.counter("serve.rejected").inc()
        elif status == "error":
            self.registry.counter("serve.errors").inc()
        rec = schema.serve_request_record(self.run_id, rows, **fields)
        self.bus.emit(rec)
        return rec

    def serve_latency(self, *, requests: int, **fields) -> dict:
        """Emit (and return) a ``serve_latency`` record — one serving
        rollup (``serve.queue.latency_summary``) — mirroring the
        headline numbers into gauges (``serve.qps`` / ``serve.p50_ms``
        / ``serve.p99_ms`` / ``serve.queue_depth``) so dashboards read
        them off the registry snapshot."""
        for g in ("qps", "p50_ms", "p99_ms", "queue_depth"):
            v = fields.get(g)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.registry.gauge(f"serve.{g}").set(v)
        rec = schema.serve_latency_record(self.run_id, requests,
                                          **fields)
        self.bus.emit(rec)
        return rec

    def scaling_curve(self, *, name: str, points: list,
                      **fields) -> dict:
        """Emit (and return) a ``scaling_curve`` record — one
        weak-scaling ladder (``obs.scaling`` / ``benchmarks.run.
        run_ladder``) — mirroring the headline shape numbers into
        gauges (``scaling.<name>.efficiency_floor`` — the curve's
        worst point — and ``scaling.<name>.serial_fraction``) and
        counting contention-contaminated points
        (``scaling.contended_points``), so a ladder's trust story
        rides every run summary."""
        eff = [e for e in (fields.get("efficiency") or [])
               if isinstance(e, (int, float)) and not isinstance(e, bool)]
        if eff:
            self.registry.gauge(
                f"scaling.{name}.efficiency_floor").set(min(eff))
        s = fields.get("serial_fraction")
        if isinstance(s, (int, float)) and not isinstance(s, bool):
            self.registry.gauge(f"scaling.{name}.serial_fraction").set(s)
        flagged = fields.get("contention_flagged")
        if isinstance(flagged, int) and flagged:
            self.registry.counter("scaling.contended_points").inc(flagged)
        rec = schema.scaling_curve_record(self.run_id, name, points,
                                          **fields)
        self.bus.emit(rec)
        return rec

    def skew_estimate(self, *, skew: float, **fields) -> dict:
        """Emit (and return) a ``skew_estimate`` record — one skew sync
        of the straggler scheduler (``resilience.scheduler``) —
        mirroring the skew into the ``sched.skew`` gauge so per-host
        imbalance rides every run summary's metrics snapshot."""
        self.registry.gauge("sched.skew").set(float(skew))
        rec = schema.skew_estimate_record(self.run_id, skew, **fields)
        self.bus.emit(rec)
        return rec

    def rebalance(self, *, at_iter: int, **fields) -> dict:
        """Emit (and return) a ``rebalance`` record — one applied
        generation-boundary rebalance decision
        (``resilience.scheduler``) — and count it
        (``sched.rebalances``)."""
        self.registry.counter("sched.rebalances").inc()
        rec = schema.rebalance_record(self.run_id, at_iter, **fields)
        self.bus.emit(rec)
        return rec

    def canary(self, *, generation: int, verdict: str,
               **fields) -> dict:
        """Emit (and return) a ``canary`` record — one shadow-served
        candidate evaluation (``pipeline.canary``) — counted overall
        (``pipeline.canaries``) and per verdict
        (``pipeline.canary.<verdict>``)."""
        self.registry.counter("pipeline.canaries").inc()
        self.registry.counter(f"pipeline.canary.{verdict}").inc()
        rec = schema.canary_record(self.run_id, generation, verdict,
                                   **fields)
        self.bus.emit(rec)
        return rec

    def promotion(self, *, decision: str, **fields) -> dict:
        """Emit (and return) a ``promotion`` record — one typed
        promotion decision (``pipeline.promote``: promoted / rejected /
        rolled_back) — counted per decision
        (``pipeline.<decision>``)."""
        self.registry.counter(f"pipeline.{decision}").inc()
        rec = schema.promotion_record(self.run_id, decision, **fields)
        self.bus.emit(rec)
        return rec

    def fleet_route(self, *, decision: str, **fields) -> dict:
        """Emit (and return) a ``fleet_route`` record — one routing
        decision of the serve fleet router (``serve.router``: route /
        hedge / retry / shed_tenant) — counted overall
        (``fleet.routes``) and per decision
        (``fleet.route.<decision>``)."""
        self.registry.counter("fleet.routes").inc()
        self.registry.counter(f"fleet.route.{decision}").inc()
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.fleet_route_record(self.run_id, decision, **fields)
        self.bus.emit(rec)
        return rec

    def replica_verdict(self, *, replica: int, verdict: str,
                        **fields) -> dict:
        """Emit (and return) a ``replica_verdict`` record — one
        replica-health classification change (``serve.router``, from
        ``HostMonitor.verdicts()``: ok / slow / lost) — counted per
        verdict (``fleet.verdict.<verdict>``)."""
        self.registry.counter(f"fleet.verdict.{verdict}").inc()
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.replica_verdict_record(self.run_id, replica,
                                            verdict, **fields)
        self.bus.emit(rec)
        return rec

    def shard_quarantine(self, *, shard: str, **fields) -> dict:
        """Emit (and return) a ``shard_quarantine`` record — one
        poisoned-shard quarantine decision of the streaming data plane
        (``data.streaming``) — and count it (``stream.quarantined``),
        so a degraded epoch is visible in every run summary."""
        self.registry.counter("stream.quarantined").inc()
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.shard_quarantine_record(self.run_id, shard,
                                             **fields)
        self.bus.emit(rec)
        return rec

    def stream_epoch(self, *, epoch: int, batches: int,
                     **fields) -> dict:
        """Emit (and return) a ``stream_epoch`` record — one completed
        streamed pass (``data.streaming.make_streaming_smooth``) —
        counting passes and batches (``stream.epochs`` /
        ``stream.batches``) and mirroring the prefetch stall fraction
        into the ``stream.stall_fraction`` gauge so overlap health
        rides the metrics snapshot."""
        self.registry.counter("stream.epochs").inc()
        self.registry.counter("stream.batches").inc(int(batches))
        sf = fields.get("stall_fraction")
        if isinstance(sf, (int, float)) and not isinstance(sf, bool):
            self.registry.gauge("stream.stall_fraction").set(sf)
        fields.setdefault("timestamp_unix", round(_time.time(), 3))
        rec = schema.stream_epoch_record(self.run_id, epoch, batches,
                                         **fields)
        self.bus.emit(rec)
        return rec

    def run_summary(self, *, tool: str, **fields) -> dict:
        """Emit (and return) the end-of-run ``run`` record, with the
        registry snapshot attached under ``metrics``."""
        rec = schema.run_record(tool=tool, run_id=self.run_id,
                                metrics=self.registry.snapshot(),
                                **fields)
        self.bus.emit(rec)
        return rec

    @property
    def records(self) -> List[dict]:
        """Everything the in-memory sink collected (empty when explicit
        sinks were passed without one)."""
        return list(self._mem.records) if self._mem is not None else []

    def iterations(self, algorithm: Optional[str] = None) -> List[dict]:
        """The in-memory iteration records, in iter order."""
        recs = [r for r in self.records if r.get("kind") == "iteration"
                and (algorithm is None or r.get("algorithm") == algorithm)]
        return sorted(recs, key=lambda r: r["iter"])

    def spans(self, name: Optional[str] = None) -> List[dict]:
        return [r for r in self.records if r.get("kind") == "span"
                and (name is None or r.get("name") == name)]

    # -- lifecycle --------------------------------------------------------
    def flush(self) -> None:
        self.bus.flush()

    def close(self) -> None:
        self.bus.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
