"""``Telemetry``: the one object threaded through ``api.run`` /
``api.make_runner`` (and the L-BFGS runners) as ``telemetry=``.

Bundles the three telemetry primitives:

- a :class:`~spark_agd_tpu.obs.registry.MetricsRegistry` (counters,
  gauges, span timers) — the passive accumulator;
- an :class:`~spark_agd_tpu.obs.events.EventBus` over pluggable sinks —
  the active stream.  Spans emit one ``span`` record as they close;
- the **live in-loop iteration stream**: :meth:`iteration_callback`
  returns the host function ``core.agd`` / ``core.lbfgs`` invoke via
  ``jax.debug.callback`` from INSIDE the compiled ``lax.while_loop`` —
  per-iteration records (iter, loss, L, theta, step, restarted) arrive
  while the program runs, not after ``block_until_ready``.

**Overhead caveat**: the callback adds a host round-trip per iteration
(an outfeed on TPU), which is exactly the traffic the fused design
removed — so telemetry is strictly opt-in (``telemetry=None`` compiles
the identical program as before, no callback in the HLO) and tier-1 /
benchmark timings are unaffected by this subsystem existing.  Enable it
for debugging convergence, watching long production fits, or feeding
dashboards; disable it when timing.  ``every=N`` thins the emitted
stream N:1 host-side (the callback still fires per iteration — thinning
bounds sink I/O, not the round-trip).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from . import schema
from .events import EventBus
from .registry import MetricsRegistry
from .sinks import InMemorySink, Sink


# callback kwarg -> canonical record field (the cores pass their
# internal names; records use the schema's)
_FIELD_NAMES = {"big_l": "L"}


def _scalar(v):
    """Host-side normalize one callback value (np scalar -> python)."""
    try:
        v = v.item()
    except AttributeError:
        pass
    if isinstance(v, bool):
        return v
    if isinstance(v, int):
        return v
    return float(v)


class Telemetry:
    """See module docstring.  With no ``sinks`` argument an in-memory
    sink is created so :attr:`records` / :meth:`iterations` work out of
    the box; pass explicit sinks (``JSONLSink``, ``CSVSink``,
    ``LoggingSink``, ``TensorBoardSink``) to stream elsewhere.

    ``host_mode``: ``"all"`` (default; single-host no-op) or
    ``"primary"`` (rank-0-only emission on multihost jobs) — see
    ``obs.events.EventBus``.
    """

    def __init__(self, sinks: Optional[Iterable[Sink]] = None, *,
                 registry: Optional[MetricsRegistry] = None,
                 every: int = 1, host_mode: str = "all",
                 run_id: Optional[str] = None):
        self.run_id = run_id or schema.new_run_id()
        self.registry = registry or MetricsRegistry()
        self._mem: Optional[InMemorySink] = None
        if sinks is None:
            self._mem = InMemorySink()
            sinks = [self._mem]
        else:
            sinks = list(sinks)
            for s in sinks:
                if isinstance(s, InMemorySink):
                    self._mem = s
                    break
        self.bus = EventBus(sinks, host_mode=host_mode)
        self.every = max(1, int(every))
        self.registry.set_span_hook(self._on_span)

    # -- spans ------------------------------------------------------------
    def _on_span(self, name: str, seconds: float) -> None:
        self.bus.emit(schema.span_record(self.run_id, name, seconds))

    def span(self, name: str):
        """Context manager timing a phase; the duration lands in the
        registry AND streams one ``span`` record as it closes."""
        return self.registry.span(name)

    # -- the live in-loop stream ------------------------------------------
    def iteration_callback(self, algorithm: str = "agd"):
        """The host function the fused loops call via
        ``jax.debug.callback`` — one call per executed iteration, kwargs
        are the per-iteration scalars.  ``accepted=False`` calls (an
        L-BFGS iteration whose line search failed — not an executed
        iteration) are counted but not emitted, preserving the
        one-record-per-iteration contract."""
        emitted = self.registry.counter(f"{algorithm}.iterations")
        rejected = self.registry.counter(f"{algorithm}.rejected_steps")
        every = self.every
        run_id = self.run_id
        bus = self.bus

        def on_iteration(**fields):
            accepted = fields.pop("accepted", None)
            if accepted is not None and not bool(accepted):
                rejected.inc()
                return
            it = int(fields.pop("it"))
            emitted.inc()
            if every > 1 and it % every:
                return
            bus.emit(schema.iteration_record(
                run_id, algorithm, it,
                **{_FIELD_NAMES.get(k, k): _scalar(v)
                   for k, v in fields.items()}))

        return on_iteration

    # -- records ----------------------------------------------------------
    def emit(self, record: dict) -> None:
        self.bus.emit(record)

    def run_summary(self, *, tool: str, **fields) -> dict:
        """Emit (and return) the end-of-run ``run`` record, with the
        registry snapshot attached under ``metrics``."""
        rec = schema.run_record(tool=tool, run_id=self.run_id,
                                metrics=self.registry.snapshot(),
                                **fields)
        self.bus.emit(rec)
        return rec

    @property
    def records(self) -> List[dict]:
        """Everything the in-memory sink collected (empty when explicit
        sinks were passed without one)."""
        return list(self._mem.records) if self._mem is not None else []

    def iterations(self, algorithm: Optional[str] = None) -> List[dict]:
        """The in-memory iteration records, in iter order."""
        recs = [r for r in self.records if r.get("kind") == "iteration"
                and (algorithm is None or r.get("algorithm") == algorithm)]
        return sorted(recs, key=lambda r: r["iter"])

    def spans(self, name: Optional[str] = None) -> List[dict]:
        return [r for r in self.records if r.get("kind") == "span"
                and (name is None or r.get("name") == name)]

    # -- lifecycle --------------------------------------------------------
    def flush(self) -> None:
        self.bus.flush()

    def close(self) -> None:
        self.bus.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
