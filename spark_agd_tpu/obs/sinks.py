"""Pluggable event sinks: where telemetry records go.

Every sink consumes plain-dict records (the ``obs.schema`` shapes) via
``emit(record)`` and supports ``flush()`` / ``close()``.  Sinks must be
cheap and never throw into the hot path — a telemetry failure must not
kill the run it observes (the same isolation rule the bench applies to
its ride-alongs).

Built-ins: in-memory (tests, programmatic access), JSONL file (the
canonical machine-readable channel — one ``obs.schema`` record per
line), CSV (spreadsheet-friendly iteration streams), stdlib logging
(human-readable lines on the ``spark_agd_tpu`` logger), and TensorBoard
behind an import guard (the container does not bake TF in; constructing
the sink without it raises a clear error, importing this module never
does).
"""

from __future__ import annotations

import csv
import io
import json
import logging
from typing import Dict, List, Optional

logger = logging.getLogger("spark_agd_tpu")


class Sink:
    """Base class; subclasses override ``emit`` (required) and
    ``flush``/``close`` (optional)."""

    def emit(self, record: dict) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self.flush()


class InMemorySink(Sink):
    """Collects records in ``self.records`` — the programmatic channel
    (tests, notebooks, the ``Telemetry`` convenience accessors)."""

    def __init__(self):
        self.records: List[dict] = []

    def emit(self, record: dict) -> None:
        self.records.append(record)


class JSONLSink(Sink):
    """One JSON object per line — the canonical run-record channel
    (``obs.schema``).  ``append=True`` (default) composes with the
    artifact convention of ``benchmarks/run.py --out``."""

    def __init__(self, path: str, append: bool = True):
        self.path = path
        self._f = open(path, "a" if append else "w")

    def emit(self, record: dict) -> None:
        self._f.write(json.dumps(record, default=_jsonable) + "\n")

    def flush(self) -> None:
        if not self._f.closed:
            self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class CSVSink(Sink):
    """CSV with the header taken from the FIRST accepted record's keys;
    later records are projected onto those columns (missing -> empty,
    extra keys dropped) so the stream stays a loadable table.

    ``kinds`` filters by record ``kind`` — a full telemetry stream
    interleaves span/run records with the iteration stream, so the
    default keeps iteration rows only (the spreadsheet-shaped part);
    pass ``kinds=None`` to accept everything.
    """

    def __init__(self, path: str, kinds=("iteration",)):
        self.path = path
        self.kinds = None if kinds is None else frozenset(kinds)
        self._f = open(path, "w", newline="")
        self._writer: Optional[csv.DictWriter] = None

    def emit(self, record: dict) -> None:
        if self.kinds is not None and record.get("kind") not in self.kinds:
            return
        if self._writer is None:
            self._writer = csv.DictWriter(
                self._f, fieldnames=list(record.keys()),
                extrasaction="ignore")
            self._writer.writeheader()
        self._writer.writerow({k: record.get(k, "")
                               for k in self._writer.fieldnames})

    def flush(self) -> None:
        if not self._f.closed:
            self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class LoggingSink(Sink):
    """Human-readable key=value lines on a stdlib logger — the channel
    ``utils.logging`` already established for post-hoc records."""

    def __init__(self, log: Optional[logging.Logger] = None,
                 level: int = logging.INFO):
        self._log = log or logger
        self._level = level

    def emit(self, record: dict) -> None:
        kind = record.get("kind", "event")
        body = " ".join(f"{k}={_fmt(v)}" for k, v in record.items()
                        if k not in ("kind", "schema_version"))
        self._log.log(self._level, "[%s] %s", kind, body)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _jsonable(v):
    """Fallback serializer: numpy scalars/arrays from debug callbacks."""
    try:
        import numpy as np

        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray):
            return v.tolist()
    except ImportError:  # pragma: no cover
        pass
    return str(v)


class TensorBoardSink(Sink):  # pragma: no cover - optional dependency
    """Scalar events into a TensorBoard logdir.  Optional: constructing
    this without a TensorBoard writer implementation installed raises
    ImportError with the remedy; merely importing ``obs.sinks`` never
    requires TF."""

    def __init__(self, logdir: str):
        writer = None
        for mod, attr in (("torch.utils.tensorboard", "SummaryWriter"),
                          ("tensorboardX", "SummaryWriter")):
            try:
                writer = getattr(__import__(mod, fromlist=[attr]), attr)
                break
            except ImportError:
                continue
        if writer is None:
            raise ImportError(
                "TensorBoardSink needs torch.utils.tensorboard or "
                "tensorboardX; neither is installed (this dependency is "
                "deliberately optional)")
        self._w = writer(logdir)

    def emit(self, record: dict) -> None:
        step = int(record.get("iter", 0))
        tag_prefix = record.get("algorithm") or record.get("kind", "run")
        for k, v in record.items():
            if isinstance(v, (int, float)) and k not in ("iter",
                                                         "schema_version",
                                                         "timestamp_unix"):
                self._w.add_scalar(f"{tag_prefix}/{k}", v, step)

    def flush(self) -> None:
        self._w.flush()

    def close(self) -> None:
        self._w.close()


class _StringIOSink(Sink):
    """JSONL into a StringIO — used by the selfcheck round-trip."""

    def __init__(self):
        self.buf = io.StringIO()

    def emit(self, record: dict) -> None:
        self.buf.write(json.dumps(record, default=_jsonable) + "\n")
