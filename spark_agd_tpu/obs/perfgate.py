"""Perf-regression gate over canonical run-record JSONLs.

The BENCH_* trajectory (``BENCH_r01..r05.json``) accumulated five
rounds of stamped run records with no automated way to say "this PR
made the hot path slower".  This module is that answer: compare a
candidate JSONL against a baseline JSONL, record-by-record, on the
metrics that define "fast as the hardware allows" —

- **wall clock**: ``wall_to_eps_s`` (and its capped twin), ``wall_s``,
  ``iters_per_sec``, ``compile_s``;
- **iterations-to-tolerance**: ``iters`` when both runs stopped under
  their own rule (``converged``);
- **compiled-program facts** (``program_cost`` records, from
  ``obs.introspect``): FLOPs, bytes accessed, peak HBM, and
  per-collective counts — the MLPerf-on-TPU-pod lesson that regression
  tracking must be tied to the compiled program's cost model, not just
  wall clock.

Records pair by a stable identity key (tool / name / config /
algorithm / dtype / pallas for runs; label / algorithm for program
costs).  Relative thresholds are configurable per metric; collective
counts gate on an *absolute* allowed increase (default 0 — a new
collective in the hot program is never noise).  Environments must
match: a gate between records whose provenance fields (platform,
device kind/count, jax/jaxlib version, mesh shape) differ is refused
unless explicitly allowed — cross-environment "regressions" are
hardware deltas, not code deltas.

Deliberately dependency-free (stdlib only), like ``obs.schema``: the
CI entry point ``tools/perf_gate.py`` must run anywhere the artifacts
exist, with or without a working jax install.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from . import schema

# metric -> (direction, default relative threshold).  direction "lower"
# = smaller is better; "higher" = larger is better.  The candidate
# regresses when it is worse by more than the threshold (relative to
# the baseline value).
RUN_METRICS: Dict[str, Tuple[str, float]] = {
    "wall_to_eps_s": ("lower", 0.15),
    "wall_to_eps_capped": ("lower", 0.15),
    "wall_s": ("lower", 0.15),
    "compile_s": ("lower", 0.50),
    "iters_per_sec": ("higher", 0.15),
    "iters_to_tol": ("lower", 0.10),
    # serving-soak summaries (tools/serve_drill.py run records): tail
    # latency is the SLO metric, so its default threshold is tight
    "p50_ms": ("lower", 0.25),
    "p99_ms": ("lower", 0.25),
    "qps": ("higher", 0.15),
    # per-host skew (obs.timeline.straggler_score, stamped onto run
    # records by the drills): lower is better, ~1.0 balanced — a
    # regression that only slows ONE host moves this metric even when
    # aggregate wall clock hides behind the fast hosts
    "straggler_score": ("lower", 0.25),
}

PROGRAM_METRICS: Dict[str, Tuple[str, float]] = {
    "flops": ("lower", 0.01),
    "bytes_accessed": ("lower", 0.05),
    "peak_hbm_bytes": ("lower", 0.05),
    "temp_bytes": ("lower", 0.10),
}

# absolute allowed increase in each collective's op count (default 0)
COLLECTIVES_METRIC = "collectives"
DEFAULT_COLLECTIVE_SLACK = 0.0

# run-record fields that define the measurement environment; a
# mismatch on any present-on-both-sides field refuses the comparison
ENV_FIELDS = ("platform", "device_kind", "n_devices", "jax_version",
              "jaxlib_version", "n_processes", "mesh_shape")

_RUN_KEY_FIELDS = ("tool", "name", "config", "algorithm", "dtype",
                   "pallas")
_PROGRAM_KEY_FIELDS = ("label", "algorithm", "tool")


@dataclasses.dataclass
class Delta:
    """One compared metric on one paired record."""

    key: str
    metric: str
    baseline: Optional[float]
    candidate: Optional[float]
    rel_change: Optional[float]  # signed; + = candidate worse
    threshold: float
    status: str  # "ok" | "regression" | "improved" | "skipped"


@dataclasses.dataclass
class GateResult:
    deltas: List[Delta]
    env_mismatches: List[str]
    unmatched_baseline: List[str]
    unmatched_candidate: List[str]
    allow_cross_env: bool = False

    @property
    def regressions(self) -> List[Delta]:
        return [d for d in self.deltas if d.status == "regression"]

    @property
    def refused(self) -> bool:
        return bool(self.env_mismatches) and not self.allow_cross_env

    @property
    def ok(self) -> bool:
        return not self.regressions and not self.refused

    def exit_code(self) -> int:
        """0 pass, 1 regression, 2 refused (cross-environment)."""
        if self.refused:
            return 2
        return 1 if self.regressions else 0


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        return f"{v:.6g}"
    return str(v)


def _table(headers: Sequence[str], rows: List[Sequence[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))

    def line(cells):
        return "  ".join(c.ljust(w)
                         for c, w in zip(cells, widths)).rstrip()

    out = [line(headers), line(["-" * w for w in widths])]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


def format_deltas(deltas: List[Delta], *,
                  only_compared: bool = False) -> str:
    """Human-readable diff table (the gate's failure output and
    ``tools/agd_report.py --compare``'s body)."""
    headers = ["key", "metric", "baseline", "candidate", "change",
               "threshold", "status"]
    rows = []
    for d in deltas:
        if only_compared and d.status == "skipped":
            continue
        change = ("-" if d.rel_change is None
                  # collective deltas are absolute op counts, the rest
                  # relative
                  else f"{d.rel_change:+g}"
                  if d.metric.startswith("collectives.")
                  else f"{d.rel_change:+.1%}")
        rows.append([d.key, d.metric, _fmt(d.baseline),
                     _fmt(d.candidate), change,
                     f"{d.threshold:g}", d.status])
    if not rows:
        return "(no comparable metrics)"
    return _table(headers, rows)


def _key(rec: dict, fields) -> str:
    parts = [f"{f}={rec[f]}" for f in fields if rec.get(f) is not None]
    return " ".join(parts) if parts else "(unkeyed)"


def _split(records: List[dict]):
    """(run_records, program_cost_records) keyed by identity; multiple
    records per key keep the LAST (the freshest measurement in an
    append-style artifact)."""
    runs: Dict[str, dict] = {}
    progs: Dict[str, dict] = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind == "program_cost":
            progs[_key(rec, _PROGRAM_KEY_FIELDS)] = rec
        elif kind == "run" or (kind is None and (
                "final_loss" in rec or "iters_per_sec" in rec)):
            # pre-schema BENCH rows gate too (legacy best-effort, like
            # tools/agd_report.py)
            runs[_key(rec, _RUN_KEY_FIELDS)] = rec
    return runs, progs


def _num(rec: dict, field: str) -> Optional[float]:
    v = rec.get(field)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if isinstance(v, float) and math.isnan(v):
        return None
    return float(v)


def _run_metric(rec: dict, metric: str) -> Optional[float]:
    if metric == "iters_to_tol":
        # only a tolerance claim when the run stopped under its own
        # rule — an iteration-capped count is the cap, not convergence
        if rec.get("converged") is not True:
            return None
        return _num(rec, "iters")
    return _num(rec, metric)


def environment_mismatches(base: dict, cand: dict,
                           key: str) -> List[str]:
    """Provenance fields present on BOTH sides that disagree."""
    out = []
    for f in ENV_FIELDS:
        b, c = base.get(f), cand.get(f)
        if b is not None and c is not None and b != c:
            out.append(f"{key}: {f} differs (baseline {b!r} vs "
                       f"candidate {c!r})")
    return out


def _compare_metric(key, metric, direction, b, c, threshold,
                    deltas: List[Delta]):
    if b is None or c is None:
        deltas.append(Delta(key, metric, b, c, None, threshold,
                            "skipped"))
        return
    if b == 0:
        rel = 0.0 if c == 0 else math.inf * (1 if c > 0 else -1)
    else:
        rel = (c - b) / abs(b)
    if direction == "higher":
        rel = -rel  # normalize: positive rel_change = worse
    status = ("regression" if rel > threshold
              else "improved" if rel < -threshold else "ok")
    deltas.append(Delta(key, metric, b, c, rel, threshold, status))


def compare_records(
    baseline: List[dict],
    candidate: List[dict],
    *,
    thresholds: Optional[Dict[str, float]] = None,
    collective_slack: float = DEFAULT_COLLECTIVE_SLACK,
    allow_cross_env: bool = False,
) -> GateResult:
    """The comparison core: pair records by identity key, compare every
    gated metric, and collect environment mismatches.  ``thresholds``
    overrides per-metric defaults (relative); ``collective_slack`` is
    the absolute op-count increase allowed per collective."""
    thresholds = dict(thresholds or {})
    b_runs, b_progs = _split(baseline)
    c_runs, c_progs = _split(candidate)

    deltas: List[Delta] = []
    env_bad: List[str] = []

    for key in sorted(set(b_runs) & set(c_runs)):
        b, c = b_runs[key], c_runs[key]
        env_bad.extend(environment_mismatches(b, c, key))
        for metric, (direction, default_thr) in RUN_METRICS.items():
            thr = thresholds.get(metric, default_thr)
            _compare_metric(key, metric, direction,
                            _run_metric(b, metric),
                            _run_metric(c, metric), thr, deltas)

    for key in sorted(set(b_progs) & set(c_progs)):
        b, c = b_progs[key], c_progs[key]
        for metric, (direction, default_thr) in PROGRAM_METRICS.items():
            thr = thresholds.get(metric, default_thr)
            _compare_metric(key, metric, direction, _num(b, metric),
                            _num(c, metric), thr, deltas)
        slack = thresholds.get(COLLECTIVES_METRIC, collective_slack)
        bc = b.get("collectives") or {}
        cc = c.get("collectives") or {}
        for op in sorted(set(bc) | set(cc)):
            bn = float(bc.get(op, 0) or 0)
            cn = float(cc.get(op, 0) or 0)
            worse = cn - bn
            status = ("regression" if worse > slack
                      else "improved" if worse < -slack else "ok")
            rel = None if bn == 0 and cn == 0 else worse
            deltas.append(Delta(key, f"collectives.{op}", bn, cn, rel,
                                slack, status))

    unmatched_b = sorted((set(b_runs) - set(c_runs))
                         | (set(b_progs) - set(c_progs)))
    unmatched_c = sorted((set(c_runs) - set(b_runs))
                         | (set(c_progs) - set(b_progs)))
    return GateResult(deltas=deltas, env_mismatches=env_bad,
                      unmatched_baseline=unmatched_b,
                      unmatched_candidate=unmatched_c,
                      allow_cross_env=allow_cross_env)


def load_records(path: str) -> List[dict]:
    """Tolerant JSONL load (non-dict lines dropped; malformed JSON
    raises ``ValueError`` naming the line, via ``schema.read_jsonl``)."""
    return [r for r in schema.read_jsonl(path) if isinstance(r, dict)]


def gate_files(baseline_path: str, candidate_path: str,
               **kwargs) -> GateResult:
    """File-level convenience: :func:`compare_records` over two
    JSONLs."""
    return compare_records(load_records(baseline_path),
                           load_records(candidate_path), **kwargs)


def format_report(result: GateResult, *, verbose: bool = False) -> str:
    """The gate's full human-readable report."""
    lines: List[str] = []
    if result.env_mismatches:
        head = ("ENVIRONMENT MISMATCH (comparison "
                + ("allowed by --allow-cross-env"
                   if result.allow_cross_env else "REFUSED") + "):")
        lines.append(head)
        lines.extend("  " + m for m in result.env_mismatches)
        lines.append("")
    reg = result.regressions
    shown = result.deltas if verbose else [
        d for d in result.deltas if d.status != "skipped"]
    if reg:
        lines.append(f"PERF GATE: {len(reg)} regression(s)")
    elif not result.refused:
        n = sum(1 for d in result.deltas if d.status != "skipped")
        lines.append(f"PERF GATE: pass ({n} metric(s) compared)")
    if shown:
        lines.append(format_deltas(shown))
    elif not result.deltas:
        lines.append("no paired records — nothing compared")
    for name, keys in (("baseline", result.unmatched_baseline),
                       ("candidate", result.unmatched_candidate)):
        if keys:
            lines.append(f"note: {len(keys)} {name}-only record "
                         f"key(s) not compared: "
                         + "; ".join(keys[:4])
                         + (" …" if len(keys) > 4 else ""))
    return "\n".join(lines)
