"""Perf-regression gate over canonical run-record JSONLs.

The BENCH_* trajectory (``BENCH_r01..r05.json``) accumulated five
rounds of stamped run records with no automated way to say "this PR
made the hot path slower".  This module is that answer: compare a
candidate JSONL against a baseline JSONL, record-by-record, on the
metrics that define "fast as the hardware allows" —

- **wall clock**: ``wall_to_eps_s`` (and its capped twin), ``wall_s``,
  ``iters_per_sec``, ``compile_s``;
- **iterations-to-tolerance**: ``iters`` when both runs stopped under
  their own rule (``converged``);
- **compiled-program facts** (``program_cost`` records, from
  ``obs.introspect``): FLOPs, bytes accessed, peak HBM, and
  per-collective counts — the MLPerf-on-TPU-pod lesson that regression
  tracking must be tied to the compiled program's cost model, not just
  wall clock.

Records pair by a stable identity key (tool / name / config /
algorithm / dtype / pallas for runs; label / algorithm for program
costs).  Relative thresholds are configurable per metric; collective
counts gate on an *absolute* allowed increase (default 0 — a new
collective in the hot program is never noise).  Environments must
match: a gate between records whose provenance fields (platform,
device kind/count, jax/jaxlib version, mesh shape, and the hardened
host identity — cpu count / governor / turbo / cgroup quota) differ is
refused unless explicitly allowed — cross-environment "regressions"
are hardware deltas, not code deltas.

**Curve-shape gating** (:func:`gate_scaling`): ``scaling_curve``
records (the ``benchmarks/run.py --ladder`` weak-scaling ladder) gate
on the SHAPE of the efficiency curve — per-point efficiency floor,
monotonicity, fitted serial-fraction ceiling, per-point deltas vs a
paired baseline curve — and REFUSE (exit 2, with a typed
``scaling_gate`` record) contention-contaminated or cross-environment
comparisons, per the BENCH_r01–r05 post-mortem: a poisoned comparison
is worse than none.

Deliberately dependency-free (stdlib only), like ``obs.schema``: the
CI entry point ``tools/perf_gate.py`` must run anywhere the artifacts
exist, with or without a working jax install.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from . import scaling as scaling_lib
from . import schema
from . import timeline as timeline_lib

# metric -> (direction, default relative threshold).  direction "lower"
# = smaller is better; "higher" = larger is better.  The candidate
# regresses when it is worse by more than the threshold (relative to
# the baseline value).
RUN_METRICS: Dict[str, Tuple[str, float]] = {
    "wall_to_eps_s": ("lower", 0.15),
    "wall_to_eps_capped": ("lower", 0.15),
    "wall_s": ("lower", 0.15),
    "compile_s": ("lower", 0.50),
    "iters_per_sec": ("higher", 0.15),
    "iters_to_tol": ("lower", 0.10),
    # serving-soak summaries (tools/serve_drill.py run records): tail
    # latency is the SLO metric, so its default threshold is tight
    "p50_ms": ("lower", 0.25),
    "p99_ms": ("lower", 0.25),
    "qps": ("higher", 0.15),
    # per-host skew (obs.timeline.straggler_score, stamped onto run
    # records by the drills): lower is better, ~1.0 balanced — a
    # regression that only slows ONE host moves this metric even when
    # aggregate wall clock hides behind the fast hosts
    "straggler_score": ("lower", 0.25),
}

PROGRAM_METRICS: Dict[str, Tuple[str, float]] = {
    "flops": ("lower", 0.01),
    "bytes_accessed": ("lower", 0.05),
    "peak_hbm_bytes": ("lower", 0.05),
    "temp_bytes": ("lower", 0.10),
}

# absolute allowed increase in each collective's op count (default 0)
COLLECTIVES_METRIC = "collectives"
DEFAULT_COLLECTIVE_SLACK = 0.0

# run-record fields that define the measurement environment; a
# mismatch on any present-on-both-sides field refuses the comparison.
# The host-identity tail (cpu count / governor / turbo / cgroup quota,
# from obs.scaling.host_fingerprint) is the BENCH_r01–r05 lesson:
# environment drift nobody stamped is indistinguishable from a code
# regression.
ENV_FIELDS = ("platform", "device_kind", "n_devices", "jax_version",
              "jaxlib_version", "n_processes", "mesh_shape",
              "cpu_count", "cpu_governor", "cpu_turbo",
              "cgroup_cpu_quota")

# scaling-curve env identity: a curve spans mesh shapes, so mesh_shape
# is a per-point fact, not curve identity
CURVE_ENV_FIELDS = tuple(f for f in ENV_FIELDS if f != "mesh_shape")

# curve-vs-baseline per-point metrics: sec_per_iter is the weak-scaling
# quantity itself; efficiency the normalized shape
CURVE_POINT_METRICS: Dict[str, Tuple[str, float]] = {
    "sec_per_iter": ("lower", 0.15),
    "efficiency": ("higher", 0.10),
}
# curve-level: the fitted serial fraction gates on ABSOLUTE increase
# (relative change near s=0 is meaningless noise)
SERIAL_FRACTION_SLACK = 0.05

_RUN_KEY_FIELDS = ("tool", "name", "config", "algorithm", "dtype",
                   "pallas")
_PROGRAM_KEY_FIELDS = ("label", "algorithm", "tool")


@dataclasses.dataclass
class Delta:
    """One compared metric on one paired record."""

    key: str
    metric: str
    baseline: Optional[float]
    candidate: Optional[float]
    rel_change: Optional[float]  # signed; + = candidate worse
    threshold: float
    status: str  # "ok" | "regression" | "improved" | "skipped"


@dataclasses.dataclass
class GateResult:
    deltas: List[Delta]
    env_mismatches: List[str]
    unmatched_baseline: List[str]
    unmatched_candidate: List[str]
    allow_cross_env: bool = False

    @property
    def regressions(self) -> List[Delta]:
        return [d for d in self.deltas if d.status == "regression"]

    @property
    def refused(self) -> bool:
        return bool(self.env_mismatches) and not self.allow_cross_env

    @property
    def ok(self) -> bool:
        return not self.regressions and not self.refused

    def exit_code(self) -> int:
        """0 pass, 1 regression, 2 refused (cross-environment)."""
        if self.refused:
            return 2
        return 1 if self.regressions else 0


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        return f"{v:.6g}"
    return str(v)


def _table(headers: Sequence[str], rows: List[Sequence[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))

    def line(cells):
        return "  ".join(c.ljust(w)
                         for c, w in zip(cells, widths)).rstrip()

    out = [line(headers), line(["-" * w for w in widths])]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


def format_deltas(deltas: List[Delta], *,
                  only_compared: bool = False) -> str:
    """Human-readable diff table (the gate's failure output and
    ``tools/agd_report.py --compare``'s body)."""
    headers = ["key", "metric", "baseline", "candidate", "change",
               "threshold", "status"]
    rows = []
    for d in deltas:
        if only_compared and d.status == "skipped":
            continue
        change = ("-" if d.rel_change is None
                  # collective deltas are absolute op counts, the rest
                  # relative
                  else f"{d.rel_change:+g}"
                  if d.metric.startswith("collectives.")
                  else f"{d.rel_change:+.1%}")
        rows.append([d.key, d.metric, _fmt(d.baseline),
                     _fmt(d.candidate), change,
                     f"{d.threshold:g}", d.status])
    if not rows:
        return "(no comparable metrics)"
    return _table(headers, rows)


def _key(rec: dict, fields) -> str:
    parts = [f"{f}={rec[f]}" for f in fields if rec.get(f) is not None]
    return " ".join(parts) if parts else "(unkeyed)"


def _split(records: List[dict]):
    """(run_records, program_cost_records) keyed by identity; multiple
    records per key keep the LAST (the freshest measurement in an
    append-style artifact)."""
    runs: Dict[str, dict] = {}
    progs: Dict[str, dict] = {}
    for rec in records:
        if not isinstance(rec, dict):
            continue
        kind = rec.get("kind")
        if kind == "program_cost":
            progs[_key(rec, _PROGRAM_KEY_FIELDS)] = rec
        elif kind == "run" or (kind is None and (
                "final_loss" in rec or "iters_per_sec" in rec)):
            # pre-schema BENCH rows gate too (legacy best-effort, like
            # tools/agd_report.py)
            runs[_key(rec, _RUN_KEY_FIELDS)] = rec
    return runs, progs


def _num(rec: dict, field: str) -> Optional[float]:
    v = rec.get(field)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if isinstance(v, float) and math.isnan(v):
        return None
    return float(v)


def _run_metric(rec: dict, metric: str) -> Optional[float]:
    if metric == "iters_to_tol":
        # only a tolerance claim when the run stopped under its own
        # rule — an iteration-capped count is the cap, not convergence
        if rec.get("converged") is not True:
            return None
        return _num(rec, "iters")
    return _num(rec, metric)


def environment_mismatches(base: dict, cand: dict,
                           key: str) -> List[str]:
    """Provenance fields present on BOTH sides that disagree."""
    out = []
    for f in ENV_FIELDS:
        b, c = base.get(f), cand.get(f)
        if b is not None and c is not None and b != c:
            out.append(f"{key}: {f} differs (baseline {b!r} vs "
                       f"candidate {c!r})")
    return out


def _compare_metric(key, metric, direction, b, c, threshold,
                    deltas: List[Delta]):
    if b is None or c is None:
        deltas.append(Delta(key, metric, b, c, None, threshold,
                            "skipped"))
        return
    if b == 0:
        rel = 0.0 if c == 0 else math.inf * (1 if c > 0 else -1)
    else:
        rel = (c - b) / abs(b)
    if direction == "higher":
        rel = -rel  # normalize: positive rel_change = worse
    status = ("regression" if rel > threshold
              else "improved" if rel < -threshold else "ok")
    deltas.append(Delta(key, metric, b, c, rel, threshold, status))


def compare_records(
    baseline: List[dict],
    candidate: List[dict],
    *,
    thresholds: Optional[Dict[str, float]] = None,
    collective_slack: float = DEFAULT_COLLECTIVE_SLACK,
    allow_cross_env: bool = False,
) -> GateResult:
    """The comparison core: pair records by identity key, compare every
    gated metric, and collect environment mismatches.  ``thresholds``
    overrides per-metric defaults (relative); ``collective_slack`` is
    the absolute op-count increase allowed per collective."""
    thresholds = dict(thresholds or {})
    b_runs, b_progs = _split(baseline)
    c_runs, c_progs = _split(candidate)

    deltas: List[Delta] = []
    env_bad: List[str] = []

    for key in sorted(set(b_runs) & set(c_runs)):
        b, c = b_runs[key], c_runs[key]
        env_bad.extend(environment_mismatches(b, c, key))
        for metric, (direction, default_thr) in RUN_METRICS.items():
            thr = thresholds.get(metric, default_thr)
            _compare_metric(key, metric, direction,
                            _run_metric(b, metric),
                            _run_metric(c, metric), thr, deltas)

    for key in sorted(set(b_progs) & set(c_progs)):
        b, c = b_progs[key], c_progs[key]
        for metric, (direction, default_thr) in PROGRAM_METRICS.items():
            thr = thresholds.get(metric, default_thr)
            _compare_metric(key, metric, direction, _num(b, metric),
                            _num(c, metric), thr, deltas)
        slack = thresholds.get(COLLECTIVES_METRIC, collective_slack)
        bc = b.get("collectives") or {}
        cc = c.get("collectives") or {}
        for op in sorted(set(bc) | set(cc)):
            bn = float(bc.get(op, 0) or 0)
            cn = float(cc.get(op, 0) or 0)
            worse = cn - bn
            status = ("regression" if worse > slack
                      else "improved" if worse < -slack else "ok")
            rel = None if bn == 0 and cn == 0 else worse
            deltas.append(Delta(key, f"collectives.{op}", bn, cn, rel,
                                slack, status))

    unmatched_b = sorted((set(b_runs) - set(c_runs))
                         | (set(b_progs) - set(c_progs)))
    unmatched_c = sorted((set(c_runs) - set(b_runs))
                         | (set(c_progs) - set(b_progs)))
    return GateResult(deltas=deltas, env_mismatches=env_bad,
                      unmatched_baseline=unmatched_b,
                      unmatched_candidate=unmatched_c,
                      allow_cross_env=allow_cross_env)


def load_records(path: str) -> List[dict]:
    """Tolerant JSONL load (non-dict lines dropped; malformed JSON
    raises ``ValueError`` naming the line, via ``schema.read_jsonl``)."""
    return [r for r in schema.read_jsonl(path) if isinstance(r, dict)]


def gate_files(baseline_path: str, candidate_path: str,
               **kwargs) -> GateResult:
    """File-level convenience: :func:`compare_records` over two
    JSONLs."""
    return compare_records(load_records(baseline_path),
                           load_records(candidate_path), **kwargs)


# update_mode distinguishes the replicated-psum and sharded-update
# ladders of the same benchmark; absent on pre-sharding records, and
# _key skips None fields, so old histories keep their keys
_CURVE_KEY_FIELDS = ("tool", "name", "algorithm", "update_mode")


def split_curves(records: List[dict]) -> Dict[str, dict]:
    """The ``scaling_curve`` records of a record list, keyed by
    identity; multiple records per key keep the LAST (the freshest
    ladder in an append-style history)."""
    out: Dict[str, dict] = {}
    for rec in records:
        if isinstance(rec, dict) and rec.get("kind") == "scaling_curve":
            out[_key(rec, _CURVE_KEY_FIELDS)] = rec
    return out


@dataclasses.dataclass
class ScalingGateResult:
    """The curve-shape gate's outcome: ``verdicts`` one per candidate
    curve (shape violations = exit 1), ``refusals`` typed reasons the
    gate would not compare at all (contaminated points, cross-
    environment baselines, quarantined records = exit 2), ``deltas``
    the per-point baseline comparison when a baseline was given."""

    verdicts: List[Tuple[str, scaling_lib.CurveVerdict]]
    refusals: List[str]
    deltas: List[Delta]
    unmatched: List[str]
    allow_cross_env: bool = False

    @property
    def shape_failures(self) -> List[str]:
        return [f for _, v in self.verdicts for f in v.failures]

    @property
    def regressions(self) -> List[Delta]:
        return [d for d in self.deltas if d.status == "regression"]

    @property
    def refused(self) -> bool:
        return bool(self.refusals) and not self.allow_cross_env

    @property
    def ok(self) -> bool:
        return not (self.refused or self.shape_failures
                    or self.regressions)

    def exit_code(self) -> int:
        """0 pass, 1 shape/regression failure, 2 refused."""
        if self.refused:
            return 2
        return 0 if not (self.shape_failures or self.regressions) else 1

    def status(self) -> str:
        return ("refused" if self.refused
                else "fail" if self.shape_failures or self.regressions
                else "pass")

    def record(self, run_id: Optional[str] = None,
               tool: str = "agd_bench") -> dict:
        """The gate's outcome as one TYPED, schema-stamped run record —
        what ``tools/agd_bench.py`` emits instead of a bare exit code,
        so a refusal is machine-readable evidence, not silence."""
        return schema.stamp({
            "name": "scaling_gate",
            "gate_status": self.status(),
            "curves": len(self.verdicts),
            "refusals": list(self.refusals),
            "shape_failures": self.shape_failures,
            "regressions": len(self.regressions),
        }, tool=tool, kind="run", run_id=run_id)


def _curve_refusals(key: str, rec: dict,
                    policy: scaling_lib.CurvePolicy,
                    verdict: scaling_lib.CurveVerdict,
                    side: str) -> List[str]:
    out = []
    if policy.contention.refuse_contended:
        out.extend(f"[{side}] {msg}" for msg in verdict.contended)
    gaps = scaling_lib.provenance_gaps(rec)
    if gaps:
        out.append(f"[{side}] {key}: quarantined — " + "; ".join(gaps))
    return out


def gate_scaling(
    candidate: List[dict],
    baseline: Optional[List[dict]] = None,
    *,
    policy: Optional[scaling_lib.CurvePolicy] = None,
    thresholds: Optional[Dict[str, float]] = None,
    allow_cross_env: bool = False,
) -> ScalingGateResult:
    """Gate ``scaling_curve`` records on CURVE SHAPE (efficiency floor
    per point, monotonicity, fitted serial-fraction ceiling) and — when
    ``baseline`` records are given — per-point deltas against the
    paired baseline curve.

    Refuses (exit 2) instead of comparing garbage: candidate or
    baseline curves with contention-contaminated points (under the
    policy's ``refuse_contended``), provenance-quarantined records
    (``obs.scaling.provenance_gaps``), and baseline pairs whose
    :data:`CURVE_ENV_FIELDS` disagree.  ``allow_cross_env`` downgrades
    every refusal to a note, mirroring the run-record gate."""
    policy = policy or scaling_lib.CurvePolicy()
    thresholds = dict(thresholds or {})
    c_curves = split_curves(candidate)
    b_curves = split_curves(baseline or [])

    verdicts: List[Tuple[str, scaling_lib.CurveVerdict]] = []
    refusals: List[str] = []
    deltas: List[Delta] = []

    if not c_curves:
        refusals.append("no scaling_curve records in the candidate — "
                        "nothing to gate")
    for key in sorted(c_curves):
        rec = c_curves[key]
        verdict = scaling_lib.check_curve(rec, policy)
        verdicts.append((key, verdict))
        refusals.extend(_curve_refusals(key, rec, policy, verdict,
                                        "candidate"))

    for key in sorted(set(b_curves) & set(c_curves)):
        b, c = b_curves[key], c_curves[key]
        b_verdict = scaling_lib.check_curve(b, policy)
        refusals.extend(_curve_refusals(key, b, policy, b_verdict,
                                        "baseline"))
        for f in CURVE_ENV_FIELDS:
            bv, cv = b.get(f), c.get(f)
            if bv is not None and cv is not None and bv != cv:
                refusals.append(
                    f"{key}: cross-environment comparison — {f} "
                    f"differs (baseline {bv!r} vs candidate {cv!r})")
        b_pts = {int(p.get("devices", 0)): p
                 for p in scaling_lib.sorted_points(b.get("points") or [])}
        c_sorted = scaling_lib.sorted_points(c.get("points") or [])
        c_eff = dict(zip((int(p.get("devices", 0)) for p in c_sorted),
                         scaling_lib.weak_scaling_efficiency(c_sorted)))
        b_sorted = scaling_lib.sorted_points(b.get("points") or [])
        b_eff = dict(zip((int(p.get("devices", 0)) for p in b_sorted),
                         scaling_lib.weak_scaling_efficiency(b_sorted)))
        for cp in c_sorted:
            k = int(cp.get("devices", 0))
            bp = b_pts.get(k)
            if bp is None:
                continue
            pkey = f"{key} devices={k}"
            for metric, (direction,
                         default_thr) in CURVE_POINT_METRICS.items():
                thr = thresholds.get(metric, default_thr)
                if metric == "efficiency":
                    bv, cv = b_eff.get(k), c_eff.get(k)
                else:
                    bv = scaling_lib.point_time(bp)
                    cv = scaling_lib.point_time(cp)
                _compare_metric(pkey, metric, direction, bv, cv, thr,
                                deltas)
        bs = scaling_lib.fit_serial_fraction(b_sorted)
        cs = scaling_lib.fit_serial_fraction(c_sorted)
        slack = thresholds.get("serial_fraction", SERIAL_FRACTION_SLACK)
        if bs is not None and cs is not None:
            worse = cs - bs
            status = ("regression" if worse > slack
                      else "improved" if worse < -slack else "ok")
            deltas.append(Delta(key, "serial_fraction", bs, cs, worse,
                                slack, status))

    unmatched = sorted(set(b_curves) - set(c_curves)) if b_curves else []
    return ScalingGateResult(verdicts=verdicts, refusals=refusals,
                             deltas=deltas, unmatched=unmatched,
                             allow_cross_env=allow_cross_env)


def format_scaling_report(result: ScalingGateResult) -> str:
    """Human-readable curve-shape gate report (the failure output of
    ``tools/agd_bench.py gate``)."""
    lines: List[str] = []
    if result.refusals:
        head = ("SCALING GATE REFUSED" if result.refused
                else "refusals waived by --allow-cross-env")
        lines.append(head + ":")
        lines.extend("  " + r for r in result.refusals)
        lines.append("")
    for key, v in result.verdicts:
        eff = ", ".join("-" if e is None else f"{e:.3f}"
                        for e in v.efficiency)
        sf = ("-" if v.serial_fraction is None
              else f"{v.serial_fraction:.3f}")
        lines.append(f"{key}: efficiency [{eff}] serial_fraction {sf} "
                     + ("OK" if not v.failures else
                        f"{len(v.failures)} shape failure(s)"))
        lines.extend("  " + f for f in v.failures)
    if result.deltas:
        lines.append("")
        lines.append(format_deltas(result.deltas, only_compared=True))
    if result.unmatched:
        lines.append(f"note: {len(result.unmatched)} baseline-only "
                     "curve(s) not compared: "
                     + "; ".join(result.unmatched[:4]))
    if not result.refused:
        lines.append("SCALING GATE: "
                     + ("pass" if result.exit_code() == 0 else
                        f"FAIL ({len(result.shape_failures)} shape, "
                        f"{len(result.regressions)} regression(s))"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Update-mode gate (parallel.sharded_update vs replicated all-reduce)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class UpdateModeGateResult:
    """The sharded-vs-replicated gate's outcome: for every benchmark
    that stamped BOTH an ``update_mode="replicated"`` and an
    ``update_mode="sharded"`` scaling curve on the same environment,
    the sharded curve's fitted serial fraction must be STRICTLY below
    the replicated one — the whole point of reduce-scattering the
    gradient is shrinking the non-parallelizable fraction, and a pair
    where it does not is a perf claim the repo must not ship.
    ``refusals`` are typed exit-2 conditions (missing mode curve,
    contention-contaminated points, provenance quarantine, cross-
    environment pair), per the BENCH post-mortem doctrine: a poisoned
    comparison is worse than none."""

    pairs: List[Tuple[str, Optional[float], Optional[float]]]
    refusals: List[str]
    failures: List[str]
    allow_cross_env: bool = False

    @property
    def refused(self) -> bool:
        return bool(self.refusals) and not self.allow_cross_env

    @property
    def ok(self) -> bool:
        return not self.refused and not self.failures

    def exit_code(self) -> int:
        """0 pass, 1 sharded not strictly better, 2 refused."""
        if self.refused:
            return 2
        return 1 if self.failures else 0

    def status(self) -> str:
        return ("refused" if self.refused
                else "fail" if self.failures else "pass")

    def record(self, run_id: Optional[str] = None,
               tool: str = "agd_bench") -> dict:
        """The gate's outcome as one typed, schema-stamped run record
        (mirrors :meth:`ScalingGateResult.record`)."""
        return schema.stamp({
            "name": "update_mode_gate",
            "gate_status": self.status(),
            "pairs": [{"key": k, "replicated_serial_fraction": r,
                       "sharded_serial_fraction": s}
                      for k, r, s in self.pairs],
            "refusals": list(self.refusals),
            "failures": list(self.failures),
        }, tool=tool, kind="run", run_id=run_id)


def _strip_mode(key: str) -> str:
    return " ".join(p for p in key.split(" ")
                    if not p.startswith("update_mode="))


def gate_update_modes(
    records: List[dict],
    *,
    policy: Optional[scaling_lib.CurvePolicy] = None,
    allow_cross_env: bool = False,
) -> UpdateModeGateResult:
    """Pair each benchmark's ``update_mode="sharded"`` scaling curve
    with its ``update_mode="replicated"`` twin (same tool / name /
    algorithm) and require the sharded fitted serial fraction strictly
    below the replicated one.

    Typed refusals (exit 2): a mode missing its twin, contention-
    contaminated points on either curve (under the policy's
    ``refuse_contended``), provenance-quarantined records, disagreeing
    :data:`CURVE_ENV_FIELDS` or stamped ``env_key`` between the pair,
    and serial fractions that cannot be fitted (< 2 points).
    ``allow_cross_env`` downgrades refusals to notes, mirroring
    :func:`gate_scaling`."""
    policy = policy or scaling_lib.CurvePolicy()
    curves = split_curves(records)
    by_mode: Dict[str, Dict[str, dict]] = {}
    for key, rec in curves.items():
        mode = rec.get("update_mode")
        if not isinstance(mode, str):
            continue
        by_mode.setdefault(_strip_mode(key), {})[mode] = rec

    pairs: List[Tuple[str, Optional[float], Optional[float]]] = []
    refusals: List[str] = []
    failures: List[str] = []

    if not by_mode:
        refusals.append("no scaling_curve records carrying update_mode "
                        "— run the ladder with --update-mode both")
    for base_key in sorted(by_mode):
        modes = by_mode[base_key]
        missing = [m for m in ("replicated", "sharded") if m not in modes]
        if missing:
            refusals.append(
                f"{base_key}: no update_mode={'/'.join(missing)} curve "
                "to pair — run both modes on this environment")
            continue
        rep, sh = modes["replicated"], modes["sharded"]
        for side, rec in (("replicated", rep), ("sharded", sh)):
            verdict = scaling_lib.check_curve(rec, policy)
            refusals.extend(
                _curve_refusals(f"{base_key} [{side}]", rec, policy,
                                verdict, side))
        for f in CURVE_ENV_FIELDS + ("env_key",):
            rv, sv = rep.get(f), sh.get(f)
            if rv is not None and sv is not None and rv != sv:
                refusals.append(
                    f"{base_key}: cross-environment pair — {f} differs "
                    f"(replicated {rv!r} vs sharded {sv!r})")
        r_sf = scaling_lib.fit_serial_fraction(
            scaling_lib.sorted_points(rep.get("points") or []))
        s_sf = scaling_lib.fit_serial_fraction(
            scaling_lib.sorted_points(sh.get("points") or []))
        pairs.append((base_key, r_sf, s_sf))
        if r_sf is None or s_sf is None:
            refusals.append(
                f"{base_key}: serial fraction not fittable on both "
                "sides (need >= 2 ladder points per mode)")
            continue
        if not s_sf < r_sf:
            failures.append(
                f"{base_key}: sharded serial fraction {s_sf:.4f} is "
                f"not strictly below replicated {r_sf:.4f} — the "
                "reduce-scatter update is not buying scalability here")
    return UpdateModeGateResult(pairs=pairs, refusals=refusals,
                                failures=failures,
                                allow_cross_env=allow_cross_env)


def format_update_mode_report(result: UpdateModeGateResult) -> str:
    """Human-readable update-mode gate report (the output of
    ``tools/agd_bench.py gate-modes``)."""
    lines: List[str] = []
    if result.refusals:
        head = ("UPDATE-MODE GATE REFUSED" if result.refused
                else "refusals waived by --allow-cross-env")
        lines.append(head + ":")
        lines.extend("  " + r for r in result.refusals)
        lines.append("")
    for key, r_sf, s_sf in result.pairs:
        lines.append(
            f"{key}: serial_fraction replicated {_fmt(r_sf)} vs "
            f"sharded {_fmt(s_sf)}")
    lines.extend("  " + f for f in result.failures)
    if not result.refused:
        lines.append("UPDATE-MODE GATE: "
                     + ("pass (sharded strictly lower)"
                        if result.ok else
                        f"FAIL ({len(result.failures)} pair(s) not "
                        "strictly better)"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Rebalance-effectiveness gate (resilience.scheduler)
# ---------------------------------------------------------------------------

# the host-local step span the rebalance gate scores (in lockstep SPMD
# the coupled "segment" spans tie; skew lives in the boundary spans —
# the same attribution rule tools/dist_fault_drill.py pins)
REBALANCE_STEP_SPAN = "boundary"

# spans shorter than this floor are host noise, not work: without it
# the straggler score of two idle hosts is a ratio of scheduler jitter
REBALANCE_FLOOR_S = 1e-3


@dataclasses.dataclass
class RebalanceGateResult:
    """The rebalance-effectiveness gate's outcome: a run that carries
    ``rebalance`` recovery actions must show its post-rebalance
    straggler score BELOW the pre-rebalance value.  ``refusals``
    (missing spans, one-sided samples) are typed exit-2 conditions —
    per the repo's gating doctrine, a comparison that cannot be made
    honestly is refused, not passed."""

    rebalances: List[dict]
    rebalance_iter: Optional[int]
    pre_score: Optional[float]
    post_score: Optional[float]
    pre_steps: Dict[int, int]
    post_steps: Dict[int, int]
    refusals: List[str]
    margin: float = 0.0

    @property
    def improved(self) -> bool:
        return (self.pre_score is not None
                and self.post_score is not None
                and self.post_score
                <= self.pre_score * (1.0 - self.margin)
                and self.post_score < self.pre_score)

    @property
    def refused(self) -> bool:
        return bool(self.refusals)

    @property
    def ok(self) -> bool:
        if self.refused:
            return False
        return self.improved if self.rebalances else True

    def exit_code(self) -> int:
        """0 pass, 1 rebalance did not lower the straggler score,
        2 refused (spans missing / nothing comparable)."""
        if self.refused:
            return 2
        return 0 if self.ok else 1


def gate_rebalance(records: List[dict], *,
                   step_span: str = REBALANCE_STEP_SPAN,
                   min_steps: int = 2,
                   margin: float = 0.0,
                   floor_s: float = REBALANCE_FLOOR_S,
                   require_rebalance: bool = False
                   ) -> RebalanceGateResult:
    """Gate rebalance effectiveness over one run's records: split the
    host-local ``step_span`` spans at the FIRST rebalance boundary and
    require the post-rebalance ``obs.timeline.straggler_score`` below
    the pre-rebalance one.  Spans are floored at ``floor_s`` (see
    :data:`REBALANCE_FLOOR_S`).  Without rebalance records the gate
    passes vacuously unless ``require_rebalance`` (then: typed
    refusal).  Missing or one-sided spans refuse (exit 2) — the
    claim "the rebalance helped" cannot be graded without timings on
    both sides."""
    rebalances = [r for r in records if isinstance(r, dict)
                  and ((r.get("kind") == "recovery"
                        and r.get("action") == "rebalance")
                       or r.get("kind") == "rebalance")]
    iters = [v for r in rebalances
             if isinstance(v := r.get("at_iter", r.get("from_iter")),
                           int) and not isinstance(v, bool)]
    refusals: List[str] = []
    if not rebalances:
        if require_rebalance:
            refusals.append("no rebalance records in the stream — "
                            "nothing to gate")
        return RebalanceGateResult(
            rebalances=[], rebalance_iter=None, pre_score=None,
            post_score=None, pre_steps={}, post_steps={},
            refusals=refusals, margin=margin)
    if not iters:
        refusals.append("rebalance records carry no at_iter/from_iter "
                        "— cannot place the boundary")
        return RebalanceGateResult(
            rebalances=rebalances, rebalance_iter=None, pre_score=None,
            post_score=None, pre_steps={}, post_steps={},
            refusals=refusals, margin=margin)
    boundary = min(iters)

    pre: Dict[int, List[float]] = {}
    post: Dict[int, List[float]] = {}
    for s in timeline_lib.collect_spans(records):
        if s.name != step_span or s.truncated:
            continue
        it = s.record.get("start_iter")
        if not isinstance(it, int) or isinstance(it, bool):
            continue
        side = pre if it < boundary else post
        side.setdefault(s.process, []).append(
            max(float(s.seconds), floor_s))
    if not pre and not post:
        refusals.append(
            f"no closed {step_span!r} spans with start_iter in the "
            "stream — run with telemetry/tracing to grade a rebalance")
    else:
        for label, side in (("pre", pre), ("post", post)):
            short = [p for p, ts in sorted(side.items())
                     if len(ts) < min_steps]
            if not side:
                refusals.append(f"no {label}-rebalance {step_span!r} "
                                "spans")
            elif short:
                refusals.append(
                    f"{label}-rebalance side has < {min_steps} "
                    f"samples for host(s) {short}")
    pre_score = timeline_lib.straggler_score(pre) if pre else None
    post_score = timeline_lib.straggler_score(post) if post else None
    if not refusals and (pre_score is None or post_score is None):
        refusals.append("straggler score not computable on both sides "
                        "(degenerate timings)")
    return RebalanceGateResult(
        rebalances=rebalances, rebalance_iter=boundary,
        pre_score=pre_score, post_score=post_score,
        pre_steps={p: len(ts) for p, ts in sorted(pre.items())},
        post_steps={p: len(ts) for p, ts in sorted(post.items())},
        refusals=refusals, margin=margin)


def format_rebalance_report(result: RebalanceGateResult) -> str:
    """Human-readable rebalance-gate report (the failure output of
    ``tools/perf_gate.py --rebalance``)."""
    lines: List[str] = []
    if result.refusals:
        lines.append("REBALANCE GATE REFUSED:")
        lines.extend("  " + r for r in result.refusals)
        return "\n".join(lines)
    if not result.rebalances:
        return ("REBALANCE GATE: pass (no rebalance records — nothing "
                "to gate)")
    lines.append(
        f"rebalance at iteration {result.rebalance_iter} "
        f"({len(result.rebalances)} record(s)); straggler score "
        f"{_fmt(result.pre_score)} -> {_fmt(result.post_score)} "
        f"(pre {result.pre_steps} / post {result.post_steps} steps)")
    lines.append("REBALANCE GATE: "
                 + ("pass (post-rebalance straggler score is lower)"
                    if result.ok else
                    "FAIL (rebalance did not lower the straggler "
                    "score)"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Promotion gate (pipeline.canary -> pipeline.promote)
# ---------------------------------------------------------------------------

# relative held-out-loss regression allowed before a candidate fails
# the quality leg (models.evaluation.log_loss, lower is better)
DEFAULT_QUALITY_THRESHOLD = 0.05

# a shadow latency summary over fewer requests than this is sampling
# noise, not evidence — the gate refuses rather than judging on it
DEFAULT_MIN_SHADOW_REQUESTS = 16

# the latency leg's metrics: candidate shadow percentiles vs the HEAD
# leg's, on the serving SLO thresholds RUN_METRICS already defines
PROMOTION_LATENCY_METRICS = ("p50_ms", "p99_ms")


@dataclasses.dataclass
class PromotionGateResult:
    """The promotion gate's outcome over ``canary`` records: a
    candidate generation promotes only when BOTH legs hold — held-out
    quality within ``quality_threshold`` of the baseline, and shadow
    p50/p99 within the serving-SLO thresholds of the HEAD leg.
    ``refusals`` are typed exit-2 conditions (too few shadow requests,
    cross-generation spec mismatch, contention-flagged latency, missing
    evidence), per the repo's gating doctrine: a comparison that cannot
    be made honestly is refused, not passed."""

    canaries: List[dict]
    deltas: List[Delta]
    refusals: List[str]
    failures: List[str]

    @property
    def refused(self) -> bool:
        return bool(self.refusals)

    @property
    def ok(self) -> bool:
        return not self.refused and not self.failures

    def exit_code(self) -> int:
        """0 pass, 1 a gate leg regressed, 2 refused."""
        if self.refused:
            return 2
        return 1 if self.failures else 0

    def status(self) -> str:
        return ("refused" if self.refused
                else "fail" if self.failures else "pass")

    def record(self, run_id: Optional[str] = None,
               tool: str = "pipeline") -> dict:
        """The gate's outcome as one typed, schema-stamped run record
        (mirrors :meth:`UpdateModeGateResult.record`)."""
        return schema.stamp({
            "name": "promotion_gate",
            "gate_status": self.status(),
            "canaries": len(self.canaries),
            "refusals": list(self.refusals),
            "failures": list(self.failures),
        }, tool=tool, kind="run", run_id=run_id)


def gate_promotion(records: List[dict], *,
                   quality_threshold: float = DEFAULT_QUALITY_THRESHOLD,
                   thresholds: Optional[Dict[str, float]] = None,
                   min_shadow_requests: int = DEFAULT_MIN_SHADOW_REQUESTS,
                   require_canary: bool = False
                   ) -> PromotionGateResult:
    """Gate promotion over ``canary`` records: for every canary in the
    stream, compare the quality leg (``quality_candidate`` vs
    ``quality_baseline`` held-out loss, relative ``quality_threshold``)
    and the latency leg (shadow ``p50_ms``/``p99_ms`` vs the HEAD
    leg's ``baseline_*``, on :data:`RUN_METRICS` thresholds unless
    overridden by ``thresholds``).  Without canary records the gate
    passes vacuously unless ``require_canary`` (then: typed refusal).

    Typed refusals (exit 2): fewer than ``min_shadow_requests`` shadow
    requests, a ``baseline_spec``/``candidate_spec`` disagreement (the
    engines are not serving the same model family — latency pairs are
    meaningless), a contention-flagged latency window, missing
    quality or latency evidence, and any refusal the canary controller
    itself stamped."""
    thresholds = dict(thresholds or {})
    canaries = [r for r in records if isinstance(r, dict)
                and r.get("kind") == "canary"]
    refusals: List[str] = []
    failures: List[str] = []
    deltas: List[Delta] = []
    if not canaries:
        if require_canary:
            refusals.append("no canary records in the stream — "
                            "nothing to gate")
        return PromotionGateResult(canaries=[], deltas=deltas,
                                   refusals=refusals, failures=failures)
    for rec in canaries:
        gen = rec.get("generation")
        base = rec.get("baseline_generation")
        key = (f"canary g{base}->g{gen}" if base is not None
               else f"canary g{gen}")
        for r in rec.get("refusals") or []:
            refusals.append(f"{key}: {r}")
        shadow = rec.get("shadow_requests")
        if not isinstance(shadow, int) or isinstance(shadow, bool) \
                or shadow < min_shadow_requests:
            refusals.append(
                f"{key}: too few shadow requests "
                f"({shadow!r} < {min_shadow_requests}) — the latency "
                "evidence is sampling noise")
        b_spec, c_spec = rec.get("baseline_spec"), rec.get(
            "candidate_spec")
        if b_spec is not None and c_spec is not None \
                and b_spec != c_spec:
            refusals.append(
                f"{key}: cross-generation spec mismatch — the shadow "
                "engine is not serving the HEAD model family "
                f"(baseline {b_spec!r} vs candidate {c_spec!r})")
        if rec.get("contention_flagged") is True:
            refusals.append(
                f"{key}: contention-flagged latency window — the "
                "shadow percentiles measured a noisy host, not the "
                "candidate")
        qb, qc = _num(rec, "quality_baseline"), _num(
            rec, "quality_candidate")
        if qb is None or qc is None:
            refusals.append(f"{key}: quality evidence missing "
                            "(quality_baseline/quality_candidate)")
        else:
            _compare_metric(key, "holdout_loss", "lower", qb, qc,
                            quality_threshold, deltas)
        lat_pairs = 0
        for metric in PROMOTION_LATENCY_METRICS:
            b = _num(rec, f"baseline_{metric}")
            c = _num(rec, metric)
            if b is None or c is None:
                continue
            lat_pairs += 1
            direction, default = RUN_METRICS[metric]
            _compare_metric(key, metric, direction,
                            b, c, thresholds.get(metric, default),
                            deltas)
        if lat_pairs == 0:
            refusals.append(f"{key}: latency evidence missing "
                            "(no paired baseline_*/candidate "
                            "percentiles)")
    failures.extend(
        f"{d.key}: {d.metric} regressed "
        f"{'' if d.rel_change is None else format(d.rel_change, '+.1%')}"
        f" (baseline {_fmt(d.baseline)} -> candidate "
        f"{_fmt(d.candidate)}, threshold {d.threshold:g})"
        for d in deltas if d.status == "regression")
    return PromotionGateResult(canaries=canaries, deltas=deltas,
                               refusals=refusals, failures=failures)


def format_promotion_report(result: PromotionGateResult) -> str:
    """Human-readable promotion-gate report (the output of
    ``tools/perf_gate.py --promotion``)."""
    lines: List[str] = []
    if result.refusals:
        lines.append("PROMOTION GATE REFUSED:")
        lines.extend("  " + r for r in result.refusals)
        return "\n".join(lines)
    if not result.canaries:
        return ("PROMOTION GATE: pass (no canary records — nothing "
                "to gate)")
    shown = [d for d in result.deltas if d.status != "skipped"]
    if shown:
        lines.append(format_deltas(shown))
    lines.append(
        "PROMOTION GATE: "
        + (f"pass ({len(result.canaries)} canary(s), "
           f"{len(shown)} metric(s) compared)"
           if result.ok else
           f"FAIL ({len(result.failures)} leg(s) regressed)"))
    lines.extend("  " + f for f in result.failures)
    return "\n".join(lines)


def format_report(result: GateResult, *, verbose: bool = False) -> str:
    """The gate's full human-readable report."""
    lines: List[str] = []
    if result.env_mismatches:
        head = ("ENVIRONMENT MISMATCH (comparison "
                + ("allowed by --allow-cross-env"
                   if result.allow_cross_env else "REFUSED") + "):")
        lines.append(head)
        lines.extend("  " + m for m in result.env_mismatches)
        lines.append("")
    reg = result.regressions
    shown = result.deltas if verbose else [
        d for d in result.deltas if d.status != "skipped"]
    if reg:
        lines.append(f"PERF GATE: {len(reg)} regression(s)")
    elif not result.refused:
        n = sum(1 for d in result.deltas if d.status != "skipped")
        lines.append(f"PERF GATE: pass ({n} metric(s) compared)")
    if shown:
        lines.append(format_deltas(shown))
    elif not result.deltas:
        lines.append("no paired records — nothing compared")
    for name, keys in (("baseline", result.unmatched_baseline),
                       ("candidate", result.unmatched_candidate)):
        if keys:
            lines.append(f"note: {len(keys)} {name}-only record "
                         f"key(s) not compared: "
                         + "; ".join(keys[:4])
                         + (" …" if len(keys) > 4 else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Fleet gate (serve.router traffic shift away from a chaos-slowed replica)
# ---------------------------------------------------------------------------

# the slowed replica must lose at least this fraction of its pre-chaos
# traffic share for the router's "routes around slow hosts" claim to
# count as demonstrated
DEFAULT_FLEET_MIN_DROP = 0.25

# a traffic-share comparison over fewer routed requests than this on
# either side of the chaos boundary is sampling noise — refuse
DEFAULT_FLEET_MIN_REQUESTS = 8


@dataclasses.dataclass
class FleetGateResult:
    """The fleet-routing gate's outcome: after a ``slow_replica``
    chaos fault fires, the slowed replica's share of routed traffic
    (``fleet_route`` records, who actually served) must drop by at
    least ``min_drop`` relative to its pre-chaos share.  Typed exit-2
    refusals for comparisons that cannot be made honestly: no routes,
    no chaos boundary, too few requests on a side, or a contaminated
    window (the slowed replica was evicted or killed mid-window — its
    share then drops for a reason that is NOT routing policy)."""

    slow_replica: Optional[int]
    boundary_unix: Optional[float]
    pre_share: Optional[float]
    post_share: Optional[float]
    pre_counts: Dict[int, int]
    post_counts: Dict[int, int]
    refusals: List[str]
    min_drop: float = DEFAULT_FLEET_MIN_DROP

    @property
    def shifted(self) -> bool:
        return (self.pre_share is not None
                and self.post_share is not None
                and self.post_share
                <= self.pre_share * (1.0 - self.min_drop))

    @property
    def refused(self) -> bool:
        return bool(self.refusals)

    @property
    def ok(self) -> bool:
        return not self.refused and self.shifted

    def exit_code(self) -> int:
        """0 pass, 1 the router did not shift traffic, 2 refused."""
        if self.refused:
            return 2
        return 0 if self.ok else 1

    def status(self) -> str:
        return ("refused" if self.refused
                else "pass" if self.ok else "fail")

    def record(self, run_id: Optional[str] = None,
               tool: str = "fleet_drill") -> dict:
        """The gate's outcome as one TYPED, schema-stamped run record
        (the same evidence discipline as the other gates: a refusal is
        machine-readable, not silence)."""
        return schema.stamp({
            "name": "fleet_gate",
            "gate_status": self.status(),
            "slow_replica": self.slow_replica,
            "pre_share": self.pre_share,
            "post_share": self.post_share,
            "refusals": list(self.refusals),
        }, tool=tool, kind="run", run_id=run_id)


def _fleet_routes(records: List[dict]) -> List[dict]:
    return [r for r in records if isinstance(r, dict)
            and r.get("kind") == "fleet_route"
            and r.get("decision") in ("route", "hedge")
            and isinstance(r.get("timestamp_unix"), (int, float))]


def _fleet_served_by(rec: dict) -> Optional[int]:
    # `winner` is who actually answered (hedges); plain routes carry
    # the same value in both fields
    who = rec.get("winner", rec.get("replica"))
    if isinstance(who, bool) or not isinstance(who, int):
        return None
    return who


def gate_fleet(records: List[dict], *,
               min_requests: int = DEFAULT_FLEET_MIN_REQUESTS,
               min_drop: float = DEFAULT_FLEET_MIN_DROP,
               window_s: Optional[float] = None) -> FleetGateResult:
    """Gate the router's traffic shift over one run's records: split
    the served ``fleet_route`` records at the FIRST ``slow_replica``
    chaos record's timestamp and require the slowed replica's served
    share to drop by ``min_drop``.  ``window_s`` bounds the post-chaos
    side (default: everything after the boundary).  Contamination —
    a ``replica_evict`` recovery or ``kill_replica`` chaos against the
    slowed replica inside the comparison window — refuses: an evicted
    replica's share hits zero by EVICTION, which proves nothing about
    latency-aware routing."""
    refusals: List[str] = []
    routes = _fleet_routes(records)
    slow_faults = sorted(
        (r for r in records if isinstance(r, dict)
         and r.get("kind") == "chaos"
         and r.get("fault") == "slow_replica"
         and isinstance(r.get("timestamp_unix"), (int, float))),
        key=lambda r: r["timestamp_unix"])
    if not routes:
        refusals.append("no timestamped fleet_route records in the "
                        "stream — run the fleet with telemetry")
    if not slow_faults:
        refusals.append("no timestamped slow_replica chaos record — "
                        "no boundary to split traffic at")
    if refusals:
        return FleetGateResult(
            slow_replica=None, boundary_unix=None, pre_share=None,
            post_share=None, pre_counts={}, post_counts={},
            refusals=refusals, min_drop=min_drop)
    first = slow_faults[0]
    slow_replica = first.get("process")
    if isinstance(slow_replica, bool) or \
            not isinstance(slow_replica, int):
        return FleetGateResult(
            slow_replica=None, boundary_unix=None, pre_share=None,
            post_share=None, pre_counts={}, post_counts={},
            refusals=["slow_replica chaos record carries no process "
                      "— cannot name the slowed replica"],
            min_drop=min_drop)
    boundary = float(first["timestamp_unix"])
    end = boundary + window_s if window_s is not None else None

    pre_counts: Dict[int, int] = {}
    post_counts: Dict[int, int] = {}
    for rec in routes:
        who = _fleet_served_by(rec)
        if who is None:
            continue
        ts = float(rec["timestamp_unix"])
        if ts < boundary:
            pre_counts[who] = pre_counts.get(who, 0) + 1
        elif end is None or ts <= end:
            post_counts[who] = post_counts.get(who, 0) + 1
    pre_n, post_n = sum(pre_counts.values()), sum(post_counts.values())
    for label, n in (("pre", pre_n), ("post", post_n)):
        if n < min_requests:
            refusals.append(
                f"only {n} routed request(s) on the {label}-chaos "
                f"side (need >= {min_requests}) — not enough signal")
    if pre_counts.get(slow_replica, 0) == 0 and pre_n >= min_requests:
        refusals.append(
            f"slowed replica {slow_replica} served no pre-chaos "
            "traffic — a share of zero cannot drop")

    window_lo = min((float(r["timestamp_unix"]) for r in routes),
                    default=boundary)
    window_hi = (end if end is not None else
                 max((float(r["timestamp_unix"]) for r in routes),
                     default=boundary))
    for rec in records:
        if not isinstance(rec, dict):
            continue
        ts = rec.get("timestamp_unix")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool):
            continue
        if not window_lo <= float(ts) <= window_hi:
            continue
        if (rec.get("kind") == "recovery"
                and rec.get("action") == "replica_evict"
                and rec.get("process") == slow_replica):
            refusals.append(
                f"contaminated: replica {slow_replica} was EVICTED "
                "inside the comparison window — its share drop is "
                "eviction, not routing")
            break
        if (rec.get("kind") == "chaos"
                and rec.get("fault") == "kill_replica"
                and rec.get("process") == slow_replica):
            refusals.append(
                f"contaminated: replica {slow_replica} was KILLED "
                "inside the comparison window — its share drop is "
                "death, not routing")
            break

    pre_share = (pre_counts.get(slow_replica, 0) / pre_n
                 if pre_n else None)
    post_share = (post_counts.get(slow_replica, 0) / post_n
                  if post_n else None)
    return FleetGateResult(
        slow_replica=slow_replica, boundary_unix=boundary,
        pre_share=pre_share, post_share=post_share,
        pre_counts=dict(sorted(pre_counts.items())),
        post_counts=dict(sorted(post_counts.items())),
        refusals=refusals, min_drop=min_drop)


def format_fleet_report(result: FleetGateResult) -> str:
    """Human-readable fleet-gate report (``tools/fleet_drill.py``'s
    slow-replica leg)."""
    lines: List[str] = []
    if result.refusals:
        lines.append("FLEET GATE REFUSED:")
        lines.extend("  " + r for r in result.refusals)
        return "\n".join(lines)
    lines.append(
        f"slow replica {result.slow_replica}: served share "
        f"{_fmt(result.pre_share)} -> {_fmt(result.post_share)} "
        f"(pre {result.pre_counts} / post {result.post_counts}; "
        f"required drop >= {result.min_drop:g})")
    lines.append(
        "FLEET GATE: "
        + ("pass (router shifted traffic away from the slowed "
           "replica)" if result.ok else
           "FAIL (the slowed replica kept its traffic share)"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Streaming gate (data.streaming / tools/stream_drill.py)
# ---------------------------------------------------------------------------

# a prefetched streamed epoch that spent more than this fraction of its
# wall time BLOCKED on ingest is not overlapping host reads with device
# compute — the double-buffering claim fails (overlap floor =
# 1 - ceiling)
DEFAULT_STREAM_STALL_CEILING = 0.5

# a pass shorter than this is timer noise — such epochs inform the
# report but are not graded
DEFAULT_STREAM_MIN_PASS_S = 0.05


@dataclasses.dataclass
class StreamGateResult:
    """The streamed-ingest gate's outcome: every gradable
    ``stream_epoch`` record with ``prefetch > 0`` must keep its stall
    fraction (time blocked on ingest / pass wall time) at or below the
    ceiling — i.e. prefetch overlap ``1 - stall_fraction`` at or above
    the floor.  Typed exit-2 refusals for measurements that cannot be
    graded honestly: a contention-flagged epoch (the stall timer
    measured the scheduler, not the pipeline), a prefetched epoch
    missing its stall fields, or — under ``require_stream`` — no
    streamed epochs at all."""

    epochs: List[dict]
    graded: int
    worst_stall: Optional[float]
    worst_epoch: Optional[int]
    quarantined: int
    refusals: List[str]
    stall_ceiling: float = DEFAULT_STREAM_STALL_CEILING

    @property
    def worst_overlap(self) -> Optional[float]:
        return None if self.worst_stall is None else 1.0 - self.worst_stall

    @property
    def refused(self) -> bool:
        return bool(self.refusals)

    @property
    def ok(self) -> bool:
        if self.refused:
            return False
        if self.worst_stall is None:
            return True  # nothing prefetched: vacuous pass
        return self.worst_stall <= self.stall_ceiling

    def exit_code(self) -> int:
        """0 pass, 1 a prefetched epoch stalled past the ceiling,
        2 refused (contention-flagged / ungradable)."""
        if self.refused:
            return 2
        return 0 if self.ok else 1


def gate_stream(records: List[dict], *,
                stall_ceiling: float = DEFAULT_STREAM_STALL_CEILING,
                min_pass_s: float = DEFAULT_STREAM_MIN_PASS_S,
                require_stream: bool = False) -> StreamGateResult:
    """Gate streamed-ingest overlap over one run's records: every
    ``stream_epoch`` with ``prefetch > 0`` and a pass long enough to
    time honestly (``min_pass_s``) must hold ``stall_fraction <=
    stall_ceiling``.  Epochs without prefetch inform the report but are
    not graded (serial ingest stalls by construction).  Without any
    ``stream_epoch`` records the gate passes vacuously unless
    ``require_stream`` (then: typed refusal)."""
    epochs = [r for r in records if isinstance(r, dict)
              and r.get("kind") == "stream_epoch"]
    refusals: List[str] = []
    if not epochs and require_stream:
        refusals.append("no stream_epoch records in the stream — run "
                        "the streamed fit with telemetry")
    worst_stall: Optional[float] = None
    worst_epoch: Optional[int] = None
    graded = 0
    for rec in epochs:
        if rec.get("contention_flagged") is True:
            refusals.append(
                f"epoch {rec.get('epoch')}: contention-flagged "
                "streamed epoch — its stall timings measured the "
                "scheduler, not the prefetch pipeline")
            continue
        prefetch = rec.get("prefetch")
        if isinstance(prefetch, bool) or not isinstance(prefetch, int) \
                or prefetch <= 0:
            continue
        stall = rec.get("stall_fraction")
        pass_s = rec.get("pass_s")
        if not isinstance(stall, (int, float)) or isinstance(stall, bool):
            refusals.append(
                f"epoch {rec.get('epoch')}: prefetched stream_epoch "
                "carries no stall_fraction — overlap cannot be graded")
            continue
        if not isinstance(pass_s, (int, float)) or isinstance(
                pass_s, bool) or float(pass_s) < min_pass_s:
            continue  # too short to time honestly; not graded
        graded += 1
        if worst_stall is None or float(stall) > worst_stall:
            worst_stall = float(stall)
            worst_epoch = rec.get("epoch")
    if require_stream and epochs and graded == 0 and not refusals:
        refusals.append(
            f"no gradable prefetched epoch (need prefetch > 0 and "
            f"pass_s >= {min_pass_s:g}) — nothing to hold to the "
            "overlap floor")
    quarantined = max((int(r.get("quarantined") or 0) for r in epochs),
                      default=0)
    return StreamGateResult(
        epochs=epochs, graded=graded, worst_stall=worst_stall,
        worst_epoch=worst_epoch, quarantined=quarantined,
        refusals=refusals, stall_ceiling=stall_ceiling)


def format_stream_report(result: StreamGateResult) -> str:
    """Human-readable stream-gate report (``tools/perf_gate.py
    --stream``)."""
    lines: List[str] = []
    if result.refusals:
        lines.append("STREAM GATE REFUSED:")
        lines.extend("  " + r for r in result.refusals)
        return "\n".join(lines)
    if not result.epochs:
        return ("STREAM GATE: pass (no stream_epoch records — nothing "
                "to gate)")
    if result.worst_stall is None:
        lines.append(
            f"{len(result.epochs)} streamed epoch(s), none prefetched "
            "— overlap not graded")
    else:
        lines.append(
            f"{len(result.epochs)} streamed epoch(s), {result.graded} "
            f"graded; worst stall fraction {_fmt(result.worst_stall)} "
            f"(epoch {result.worst_epoch}, overlap "
            f"{_fmt(result.worst_overlap)}, ceiling "
            f"{result.stall_ceiling:g})")
    if result.quarantined:
        lines.append(f"  {result.quarantined} shard(s) quarantined "
                     "during the run")
    lines.append(
        "STREAM GATE: "
        + ("pass (prefetch overlap held the floor)" if result.ok else
           "FAIL (a prefetched epoch stalled on ingest past the "
           "ceiling)"))
    return "\n".join(lines)
