"""``python -m spark_agd_tpu.obs`` — schema tooling.

``--selfcheck`` validates the example records against ``obs.schema``
(plus a JSON round-trip and a negative control) and exits nonzero on
any failure — the CI guard that the canonical run-record schema and its
validator stay in agreement.
"""

from __future__ import annotations

import argparse
import sys

from . import schema


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m spark_agd_tpu.obs", description=__doc__)
    p.add_argument("--selfcheck", action="store_true",
                   help="validate the example records against the "
                        "canonical schema; exit 1 on any failure")
    p.add_argument("--validate", metavar="FILE.jsonl",
                   help="validate every record in a JSONL file; exit 1 "
                        "if any record fails")
    args = p.parse_args(argv)
    if args.selfcheck:
        ok, msgs = schema.selfcheck()
        for m in msgs:
            print(m)
        return 0 if ok else 1
    if args.validate:
        bad = 0
        recs = schema.read_jsonl(args.validate)
        for i, rec in enumerate(recs, 1):
            errs = schema.validate_record(rec)
            if errs:
                bad += 1
                print(f"{args.validate}: record {i}: {'; '.join(errs)}")
        print(f"{args.validate}: {len(recs)} records, {bad} invalid")
        return 1 if bad else 0
    p.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
