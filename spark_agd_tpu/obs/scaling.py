"""The scaling observatory: weak-scaling curves you can trust.

The north-star question — "does this scale?" — has no answer in a
single number.  Per the MLPerf-on-TPU-pods lesson (PAPERS.md,
arXiv 1909.09756), a scaling claim is an *efficiency curve over mesh
shapes*; and per the Spark-ML profiling study (arXiv 1612.01437),
unobserved host interference is the dominant confounder — the
BENCH_r01–r05 trajectory was poisoned exactly this way (host
contention and environment drift nobody measured).  This module is the
stdlib-only analysis half of the answer; the ladder that *produces*
curves lives in ``benchmarks/run.py`` (and ``tools/agd_bench.py``
drives both from the command line):

- **host fingerprint** (:func:`host_fingerprint`): cpu count, loadavg,
  cpufreq governor / turbo state, container-cgroup CPU quota — the
  environment facts ``obs.introspect.environment_fingerprint`` now
  stamps onto every record, readable with no jax backend;
- **contention sentinel** (:class:`ContentionSentinel`): loadavg /
  hypervisor-steal / RSS sampled before, during, and after each ladder
  point, plus a calibrated :class:`SpinProbe` whose interference score
  measures *this process's* actual slowdown — every point carries its
  own contamination verdict;
- **curve math**: weak-scaling efficiency per point
  (:func:`weak_scaling_efficiency`) and a fitted serial fraction
  (:func:`fit_serial_fraction`, the Gustafson-form least-squares fit);
- **curve-shape verdicts** (:class:`CurvePolicy` /
  :func:`check_curve`): efficiency floor per point, monotonicity, and
  a serial-fraction ceiling — what ``obs.perfgate.gate_scaling`` gates
  on instead of single numbers;
- **provenance keys** (:func:`environment_key`): the stable hash
  ``tools/agd_bench.py`` keys its history JSONL on, so two records can
  only ever be compared when they were measured on the same
  environment.

Stdlib-only by contract (like ``obs.schema`` / ``obs.perfgate``): the
gate and the validator must run anywhere the artifacts exist, with or
without a working jax install.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# host facts (all best-effort: a field is absent/None where the kernel
# surface is unreadable, never a raised error)
# ---------------------------------------------------------------------------

_GOVERNOR_PATH = "/sys/devices/system/cpu/cpu0/cpufreq/scaling_governor"
_NO_TURBO_PATH = "/sys/devices/system/cpu/intel_pstate/no_turbo"
_BOOST_PATH = "/sys/devices/system/cpu/cpufreq/boost"
_CGROUP_V2_PATH = "/sys/fs/cgroup/cpu.max"
_CGROUP_V1_QUOTA = "/sys/fs/cgroup/cpu/cpu.cfs_quota_us"
_CGROUP_V1_PERIOD = "/sys/fs/cgroup/cpu/cpu.cfs_period_us"


def _read_text(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def read_cpu_governor() -> Optional[str]:
    """The cpufreq governor ("performance"/"powersave"/…), or None where
    cpufreq is not exposed (most containers)."""
    return _read_text(_GOVERNOR_PATH)


def read_turbo_state() -> Optional[str]:
    """"on"/"off" for the boost-clock state, None where unreadable.
    Turbo drift between two measurements makes their wall clocks
    incomparable, which is why this rides the environment key."""
    no_turbo = _read_text(_NO_TURBO_PATH)
    if no_turbo is not None:
        return "off" if no_turbo == "1" else "on"
    boost = _read_text(_BOOST_PATH)
    if boost is not None:
        return "on" if boost == "1" else "off"
    return None


def read_cgroup_cpu_quota() -> Optional[object]:
    """Container CPU quota in CPUs (float), the string "unlimited", or
    None where no cgroup controller is readable.  A 4-CPU-quota
    container and a bare 64-core host must never be compared."""
    v2 = _read_text(_CGROUP_V2_PATH)
    if v2 is not None:
        parts = v2.split()
        if parts and parts[0] == "max":
            return "unlimited"
        if len(parts) == 2:
            try:
                return round(float(parts[0]) / float(parts[1]), 3)
            except (ValueError, ZeroDivisionError):
                return None
    quota = _read_text(_CGROUP_V1_QUOTA)
    period = _read_text(_CGROUP_V1_PERIOD)
    if quota is not None and period is not None:
        try:
            q, p = float(quota), float(period)
        except ValueError:
            return None
        if q < 0:
            return "unlimited"
        if p > 0:
            return round(q / p, 3)
    return None


def read_steal_ticks() -> Optional[int]:
    """Cumulative hypervisor-steal ticks from ``/proc/stat`` (field 8 of
    the aggregate cpu line) — a nonzero delta across a timed region
    means the VM itself was descheduled while we measured."""
    stat = _read_text("/proc/stat")
    if not stat:
        return None
    first = stat.splitlines()[0].split()
    if first[:1] != ["cpu"] or len(first) < 9:
        return None
    try:
        return int(first[8])
    except ValueError:
        return None


def read_rss_kb() -> Optional[int]:
    """This process's resident set (kB) from ``/proc/self/status``."""
    status = _read_text("/proc/self/status")
    if not status:
        return None
    for line in status.splitlines():
        if line.startswith("VmRSS:"):
            parts = line.split()
            if len(parts) >= 2:
                try:
                    return int(parts[1])
                except ValueError:
                    return None
    return None


def read_loadavg() -> Optional[float]:
    """1-minute loadavg, None on platforms without it."""
    try:
        return round(os.getloadavg()[0], 3)
    except (OSError, AttributeError):
        return None


def host_fingerprint() -> dict:
    """The host half of ``environment_fingerprint()``: readable with no
    jax backend (so ``bench.py``'s wedged-tunnel error path stamps it
    too).  Absent-where-unreadable; ``loadavg_1m`` is measurement-time
    state (a contention signal), the rest are environment identity —
    only the identity fields enter :func:`environment_key`."""
    out: dict = {"cpu_count": os.cpu_count()}
    load = read_loadavg()
    if load is not None:
        out["loadavg_1m"] = load
    gov = read_cpu_governor()
    if gov is not None:
        out["cpu_governor"] = gov
    turbo = read_turbo_state()
    if turbo is not None:
        out["cpu_turbo"] = turbo
    quota = read_cgroup_cpu_quota()
    if quota is not None:
        out["cgroup_cpu_quota"] = quota
    return out


# the environment-identity fields a history key is derived from: stable
# per machine+container+toolchain, excluding measurement-time state
# (loadavg, steal — those are the sentinel's job, not identity)
ENV_KEY_FIELDS = ("platform", "device_kind", "n_devices", "n_processes",
                  "jax_version", "jaxlib_version", "cpu_count",
                  "cpu_governor", "cpu_turbo", "cgroup_cpu_quota")


def environment_key(fields: dict) -> str:
    """Stable provenance key over the identity subset of an environment
    fingerprint — what ``tools/agd_bench.py`` keys its history JSONL on.
    Records with different keys are never silently compared."""
    ident = {f: fields[f] for f in ENV_KEY_FIELDS if f in fields}
    digest = hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()
    return f"env-{digest[:12]}"


# ---------------------------------------------------------------------------
# the contention sentinel
# ---------------------------------------------------------------------------


class SpinProbe:
    """A calibrated fixed-work spin loop: the direct measurement of
    "how much slower does CPU work run right now vs the quiet
    baseline".  loadavg and steal see *other* processes; the probe sees
    what actually happens to THIS process's timeslices — the quantity a
    benchmark number is poisoned by.

    ``calibrate()`` takes the min over repeats as the quiet baseline
    (min is robust to one-off blips; sustained interference inflates
    every repeat, including the min).  ``score()`` is the fractional
    slowdown of a fresh min-of-repeats measurement, clamped at 0."""

    def __init__(self, work: int = 200_000):
        self.work = int(work)
        self.baseline_s: Optional[float] = None

    def _spin(self) -> float:
        # deterministic integer xorshift — no allocation, no FP, the
        # same instruction stream every call
        x, n = 0x9E3779B97F4A7C15, self.work
        t0 = time.perf_counter()
        for _ in range(n):
            x ^= (x << 13) & 0xFFFFFFFFFFFFFFFF
            x ^= x >> 7
            x ^= (x << 17) & 0xFFFFFFFFFFFFFFFF
        dt = time.perf_counter() - t0
        # keep the accumulator observable so the loop cannot be elided
        self._last = x
        return dt

    def calibrate(self, repeats: int = 5) -> float:
        self.baseline_s = min(self._spin() for _ in range(max(1, repeats)))
        return self.baseline_s

    def score(self, repeats: int = 3) -> float:
        if self.baseline_s is None:
            self.calibrate()
        best = min(self._spin() for _ in range(max(1, repeats)))
        return max(0.0, best / self.baseline_s - 1.0)


@dataclasses.dataclass(frozen=True)
class ContentionPolicy:
    """When is a ladder point contaminated?  Thresholds are generous by
    default — the sentinel must flag a genuinely-busy host, not fail a
    CI box for breathing."""

    max_spin_score: float = 0.75   # probe ran >1.75x its calibrated time
    max_steal_ticks: int = 50      # hypervisor descheduled us mid-point
    max_loadavg_jump: float = 4.0  # 1-min load rose by more than this
    # curve-level: refuse gating/comparison outright when any point is
    # flagged (set False to gate shape anyway, e.g. in noisy CI)
    refuse_contended: bool = True


def flag_contention(report: dict,
                    policy: Optional[ContentionPolicy] = None
                    ) -> Tuple[bool, List[str]]:
    """Apply a :class:`ContentionPolicy` to one sentinel report dict.
    Returns ``(flagged, reasons)``; unreadable fields never flag."""
    policy = policy or ContentionPolicy()
    reasons: List[str] = []
    spin = report.get("spin_score")
    if isinstance(spin, (int, float)) and spin > policy.max_spin_score:
        reasons.append(f"spin-probe interference score {spin:.2f} > "
                       f"{policy.max_spin_score:g}")
    steal = report.get("steal_ticks")
    if isinstance(steal, int) and steal > policy.max_steal_ticks:
        reasons.append(f"hypervisor steal {steal} ticks > "
                       f"{policy.max_steal_ticks}")
    before = report.get("loadavg_before")
    during = report.get("loadavg_during_max")
    if isinstance(before, (int, float)) and isinstance(during,
                                                       (int, float)):
        jump = during - before
        if jump > policy.max_loadavg_jump:
            reasons.append(f"loadavg jumped +{jump:.2f} > "
                           f"{policy.max_loadavg_jump:g} mid-point")
    return bool(reasons), reasons


class _Watch:
    """One watched ladder point: snapshots host state on entry and
    exit, samples loadavg/RSS from a background thread while the timed
    region runs, and spin-probes on both sides of it (never inside —
    the probe must not perturb the measurement it guards)."""

    def __init__(self, sentinel: "ContentionSentinel"):
        self._s = sentinel
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._during_load: List[float] = []
        self._during_rss: List[int] = []
        self.report: Optional[dict] = None

    def _sample_loop(self):
        while not self._stop.wait(self._s.sample_interval_s):
            load = read_loadavg()
            if load is not None:
                self._during_load.append(load)
            rss = read_rss_kb()
            if rss is not None:
                self._during_rss.append(rss)

    def __enter__(self):
        self._spin_before = self._s.probe.score()
        self._load_before = read_loadavg()
        self._steal_before = read_steal_ticks()
        self._rss_before = read_rss_kb()
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._sample_loop,
                                        daemon=True)
        self._thread.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        seconds = time.perf_counter() - self._t0
        load_after = read_loadavg()
        steal_after = read_steal_ticks()
        rss_after = read_rss_kb()
        spin_after = self._s.probe.score()
        rss_all = [v for v in (self._rss_before, rss_after) if v is not None]
        rss_all.extend(self._during_rss)
        load_during = list(self._during_load)
        if load_after is not None:
            load_during.append(load_after)
        report = {
            "seconds": round(seconds, 4),
            "loadavg_before": self._load_before,
            "loadavg_during_max": (round(max(load_during), 3)
                                   if load_during else None),
            "loadavg_after": load_after,
            "steal_ticks": (steal_after - self._steal_before
                            if None not in (steal_after,
                                            self._steal_before) else None),
            "rss_peak_kb": max(rss_all) if rss_all else None,
            "spin_score_before": round(self._spin_before, 4),
            "spin_score_after": round(spin_after, 4),
            "spin_score": round(max(self._spin_before, spin_after), 4),
        }
        flagged, reasons = flag_contention(report, self._s.policy)
        report["flagged"] = flagged
        if reasons:
            report["reasons"] = reasons
        self.report = report
        return False


class ContentionSentinel:
    """The host-contention sentinel one ladder shares across its
    points: calibrates the spin probe ONCE up front (before any timed
    work), then wraps each point in a :meth:`watch` whose report lands
    inside the point's record — so every number carries the evidence
    for (or against) its own trustworthiness."""

    def __init__(self, probe: Optional[SpinProbe] = None,
                 policy: Optional[ContentionPolicy] = None,
                 sample_interval_s: float = 0.2):
        self.probe = probe or SpinProbe()
        self.policy = policy or ContentionPolicy()
        self.sample_interval_s = float(sample_interval_s)
        if self.probe.baseline_s is None:
            self.probe.calibrate()

    def watch(self) -> _Watch:
        return _Watch(self)


# ---------------------------------------------------------------------------
# curve math
# ---------------------------------------------------------------------------


def point_time(point: dict) -> Optional[float]:
    """One point's steady-state seconds-per-iteration — the weak-scaling
    quantity (fixed per-device work: ideal scaling holds it constant as
    devices grow).  Falls back to wall/iters when ``sec_per_iter`` is
    absent; None when nothing usable is present."""
    spi = point.get("sec_per_iter")
    if isinstance(spi, (int, float)) and not isinstance(spi, bool) \
            and spi > 0:
        return float(spi)
    wall, iters = point.get("wall_s"), point.get("iters")
    if isinstance(wall, (int, float)) and not isinstance(wall, bool) \
            and isinstance(iters, int) and iters > 0 and wall > 0:
        return float(wall) / iters
    return None


def sorted_points(points: Sequence[dict]) -> List[dict]:
    """Points in ladder order (ascending device count)."""
    return sorted(points, key=lambda p: int(p.get("devices", 0)))


def weak_scaling_efficiency(points: Sequence[dict]
                            ) -> List[Optional[float]]:
    """Per-point weak-scaling efficiency ``t_1 / t_k`` (1.0 at the
    1-device reference by construction, lower as overhead grows).
    ``None`` where a point has no usable time."""
    pts = sorted_points(points)
    if not pts:
        return []
    t1 = point_time(pts[0])
    out: List[Optional[float]] = []
    for p in pts:
        tk = point_time(p)
        out.append(None if t1 is None or tk is None
                   else round(t1 / tk, 4))
    return out


def fit_serial_fraction(points: Sequence[dict]) -> Optional[float]:
    """Least-squares serial fraction ``s`` of the Gustafson weak-scaling
    model ``t_k = t_1 * ((1 - s) + s*k)``: the non-parallelizable share
    of the per-point work, fitted over every point with a usable time.
    0 is a perfectly scalable workload; the curve-shape gate puts a
    ceiling on it.  Closed form: with ``r_k = t_k/t_1``,
    ``s = Σ (k-1)(r_k - 1) / Σ (k-1)^2``, clamped to [0, 1].  None with
    fewer than two usable points."""
    pts = sorted_points(points)
    if not pts:
        return None
    t1 = point_time(pts[0])
    if t1 is None:
        return None
    num = den = 0.0
    usable = 0
    for p in pts:
        k = int(p.get("devices", 0))
        tk = point_time(p)
        if tk is None or k < 1:
            continue
        usable += 1
        num += (k - 1) * (tk / t1 - 1.0)
        den += (k - 1) ** 2
    if usable < 2 or den == 0:
        return None
    return round(min(1.0, max(0.0, num / den)), 4)


def curve_fields(points: Sequence[dict]) -> dict:
    """The derived curve-level fields of a ``scaling_curve`` record:
    ordered points, per-point efficiency, fitted serial fraction, and
    the contention census.  Callers add identity (name/algorithm), the
    environment fingerprint, and the schema stamp."""
    pts = sorted_points(points)
    eff = weak_scaling_efficiency(pts)
    flagged = sum(1 for p in pts
                  if (p.get("contention") or {}).get("flagged"))
    out = {
        "points": list(pts),
        "n_points": len(pts),
        "max_devices": int(pts[-1]["devices"]) if pts else 0,
        "efficiency": eff,
        "contention_flagged": flagged,
    }
    s = fit_serial_fraction(pts)
    if s is not None:
        out["serial_fraction"] = s
    return out


# ---------------------------------------------------------------------------
# curve-shape verdicts
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CurvePolicy:
    """What shape must a trustworthy weak-scaling curve have?

    - ``min_efficiency``: every point's efficiency floor — the headline
      "does this scale" number (MLPerf weak-scaling framing);
    - ``monotone_slack``: efficiency is physically non-increasing in
      device count; a RISE beyond this slack is a measurement artifact
      (the 1-device reference was itself contended, or the run is
      noise) and fails the curve's shape rather than flattering it;
    - ``max_serial_fraction``: ceiling on the fitted Gustafson serial
      fraction — the quantity that caps every future mesh size;
    - ``contention``: the per-point contamination policy; with
      ``refuse_contended`` the gate REFUSES (exit 2) rather than
      gating poisoned data.
    """

    min_efficiency: float = 0.5
    monotone_slack: float = 0.10
    max_serial_fraction: float = 0.30
    contention: ContentionPolicy = dataclasses.field(
        default_factory=ContentionPolicy)


@dataclasses.dataclass
class CurveVerdict:
    """One curve's shape verdict: ``failures`` are shape violations
    (gate exit 1), ``contended`` are contaminated points (refusal
    material, gate exit 2 under ``refuse_contended``)."""

    name: str
    failures: List[str]
    contended: List[str]
    efficiency: List[Optional[float]]
    serial_fraction: Optional[float]

    @property
    def ok(self) -> bool:
        return not self.failures and not self.contended


def check_curve(rec: dict,
                policy: Optional[CurvePolicy] = None) -> CurveVerdict:
    """Shape-check one ``scaling_curve`` record against a
    :class:`CurvePolicy` — efficiency floor per point, monotonicity,
    serial-fraction ceiling, and the per-point contention census."""
    policy = policy or CurvePolicy()
    name = str(rec.get("name", "?"))
    pts = sorted_points(rec.get("points") or [])
    eff = rec.get("efficiency")
    if not isinstance(eff, list) or len(eff) != len(pts):
        eff = weak_scaling_efficiency(pts)
    s = rec.get("serial_fraction")
    if not isinstance(s, (int, float)) or isinstance(s, bool):
        s = fit_serial_fraction(pts)
    failures: List[str] = []
    contended: List[str] = []
    if len(pts) < 2:
        failures.append(f"{name}: {len(pts)} point(s) — a curve needs "
                        "at least 2 mesh shapes")
    prev_eff: Optional[float] = None
    for p, e in zip(pts, eff):
        k = p.get("devices", "?")
        cont = p.get("contention") or {}
        if cont.get("flagged"):
            why = "; ".join(cont.get("reasons", [])) or "flagged"
            contended.append(f"{name}: point devices={k} is "
                             f"contention-contaminated ({why})")
        if e is None:
            failures.append(f"{name}: point devices={k} has no usable "
                            "time (wall_s/iters or sec_per_iter)")
            continue
        if e < policy.min_efficiency:
            failures.append(
                f"{name}: efficiency {e:.3f} at devices={k} below the "
                f"{policy.min_efficiency:g} floor")
        if prev_eff is not None and e > prev_eff + policy.monotone_slack:
            failures.append(
                f"{name}: non-monotone — efficiency rose {prev_eff:.3f}"
                f" -> {e:.3f} at devices={k} (beyond the "
                f"{policy.monotone_slack:g} slack; the smaller rung was "
                "likely itself contended)")
        prev_eff = e
    if s is not None and s > policy.max_serial_fraction:
        failures.append(f"{name}: fitted serial fraction {s:.3f} above "
                        f"the {policy.max_serial_fraction:g} ceiling")
    return CurveVerdict(name=name, failures=failures,
                        contended=contended, efficiency=list(eff),
                        serial_fraction=(round(float(s), 4)
                                         if isinstance(s, (int, float))
                                         and not isinstance(s, bool)
                                         else None))


# ---------------------------------------------------------------------------
# provenance validation (the legacy-artifact quarantine)
# ---------------------------------------------------------------------------

# what a record must carry to participate in history comparisons
_PROVENANCE_FIELDS = ("platform", "jax_version", "jaxlib_version")


def provenance_gaps(rec: dict) -> List[str]:
    """Why a record may NOT enter history comparisons: missing
    environment provenance, or (for scaling curves) points without a
    contention report.  An empty list means the record is trusted.
    Legacy ``BENCH_r0*.json`` wrapper rows (``{"n", "cmd", "rc",
    "tail"}`` driver logs, pre-schema) are quarantined wholesale."""
    if not isinstance(rec, dict):
        return ["not a record (not a JSON object)"]
    if {"cmd", "rc"} <= set(rec) and "kind" not in rec:
        return ["legacy bench driver log (pre-schema wrapper row: no "
                "kind, no provenance, no measurements to compare)"]
    gaps = [f"missing provenance field '{f}'"
            for f in _PROVENANCE_FIELDS if rec.get(f) is None]
    if rec.get("kind") == "scaling_curve":
        pts = rec.get("points") or []
        bare = [str(p.get("devices", "?")) for p in pts
                if not isinstance(p.get("contention"), dict)]
        if bare:
            gaps.append("point(s) devices=" + ",".join(bare)
                        + " carry no contention report")
        if "env_key" not in rec:
            gaps.append("missing env_key (append via tools/agd_bench.py"
                        " so history stays provenance-keyed)")
    return gaps
