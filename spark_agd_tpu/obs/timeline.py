"""Per-host timeline analysis over traced span records.

The consumption half of ``obs.trace``: given any record stream (a run
JSONL, a flight-recorder dump, or both), reconstruct the causal span
tree and answer the distributed-ML diagnosis questions of PAPERS.md
arXiv 1612.01437 — which host is slow, which chain of work bounds the
wall clock, and where a dead host's timeline stops:

- :func:`collect_spans` pairs each span's open/close records by span
  id (the close record wins; an open with no close is a **truncated**
  span — the on-disk shape of a SIGKILL);
- :func:`build_forest` links children to parents; a well-formed trace
  has ONE root and no orphans (:func:`analyze` reports
  ``connected``);
- :func:`per_host_step_times` / :func:`straggler_score` aggregate the
  ``segment`` spans per process rank — the straggler score is
  ``max over hosts of that host's p95 step time, divided by the median
  step time over all hosts' samples`` (lower is better, ~1.0 means
  balanced; ``obs.perfgate`` gates on it so a regression that only
  slows one host fails);
- :func:`critical_path` walks the tree root→leaf following the child
  whose subtree ends LAST (truncated spans inherit their deepest
  descendant's end) — the chain of work that bounded the run;
- :func:`to_chrome_trace` renders the spans as Chrome trace-event JSON
  (load via ``chrome://tracing`` or Perfetto: one row per host, spans
  nested by time).

Deliberately stdlib-only, like ``obs.schema``: ``tools/agd_trace.py``
must analyze artifacts wherever they ended up, backend or not.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

STEP_SPAN_NAME = "segment"


@dataclasses.dataclass
class Span:
    """One reconstructed span (paired open/close, or truncated)."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    process: int
    seconds: float            # 0.0 when truncated (duration unknown)
    t_start: Optional[float]  # t_start_unix when present
    status: str               # "ok" | "error" | ... | "open"
    truncated: bool
    record: dict              # the raw (closing, or lone open) record
    children: List["Span"] = dataclasses.field(default_factory=list)
    _end: Optional[float] = None

    def end(self) -> Optional[float]:
        """The span's effective end time: close time for a finished
        span, the deepest descendant's end for a truncated one (its
        own duration is unknowable — the process died)."""
        if self._end is not None:
            return self._end
        own = (None if self.t_start is None
               else self.t_start + (0.0 if self.truncated
                                    else self.seconds))
        ends = [own] + [c.end() for c in self.children]
        ends = [e for e in ends if e is not None]
        self._end = max(ends) if ends else None
        return self._end


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted non-empty list
    (same convention as ``serve.queue``)."""
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[idx]


def collect_spans(records: Sequence[dict],
                  trace_id: Optional[str] = None) -> List[Span]:
    """Pair open/close span records into :class:`Span` objects (file
    order preserved by first sighting).  Only records that carry trace
    ids participate — untraced phase spans (``compile``/``execute``)
    are not part of any tree."""
    by_id: Dict[Tuple[str, str], Span] = {}
    order: List[Tuple[str, str]] = []
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") != "span":
            continue
        tid, sid = rec.get("trace_id"), rec.get("span_id")
        if not tid or not sid:
            continue
        if trace_id is not None and tid != trace_id:
            continue
        key = (tid, sid)
        status = rec.get("status", "ok")
        span = Span(
            name=str(rec.get("name", "?")), trace_id=tid, span_id=sid,
            parent_id=rec.get("parent_id"),
            process=int(rec.get("process", 0) or 0),
            seconds=float(rec.get("seconds", 0.0) or 0.0),
            t_start=rec.get("t_start_unix"),
            status=status, truncated=(status == "open"), record=rec)
        if key not in by_id:
            order.append(key)
            by_id[key] = span
        elif status != "open":
            # the close record supersedes the open marker
            by_id[key] = span
    return [by_id[k] for k in order]


def trace_ids(records: Sequence[dict]) -> List[str]:
    """Distinct trace ids present, in first-sighting order."""
    seen: List[str] = []
    for s in collect_spans(records):
        if s.trace_id not in seen:
            seen.append(s.trace_id)
    return seen


def build_forest(spans: Sequence[Span]) -> Tuple[List[Span], int]:
    """Link children to parents; returns ``(roots, orphans)`` where an
    orphan is a span whose ``parent_id`` names a span that is not in
    the stream (it is promoted to a root so nothing is lost, but a
    connected tree has zero of them)."""
    by_id = {s.span_id: s for s in spans}
    roots: List[Span] = []
    orphans = 0
    for s in spans:
        s.children = []
        s._end = None
    for s in spans:
        if s.parent_id is None:
            roots.append(s)
        elif s.parent_id in by_id:
            by_id[s.parent_id].children.append(s)
        else:
            orphans += 1
            roots.append(s)
    for s in spans:
        s.children.sort(key=lambda c: (c.t_start is None,
                                       c.t_start or 0.0))
    return roots, orphans


def hosts_of(spans: Sequence[Span]) -> List[int]:
    return sorted({s.process for s in spans})


def per_host_step_times(records: Sequence[dict], *,
                        name: str = STEP_SPAN_NAME,
                        trace_id: Optional[str] = None,
                        skip_first: int = 0,
                        ) -> Dict[int, List[float]]:
    """Closed step-span durations keyed by process rank — the raw
    material of the skew diagnosis.  Truncated spans are excluded
    (their duration is unknown, not zero).  ``skip_first`` drops that
    many leading steps PER HOST: each host's first segment carries its
    trace+compile cost, which is warmup, not skew — steady-state skew
    diagnosis (the drills) passes 1."""
    out: Dict[int, List[float]] = defaultdict(list)
    for s in collect_spans(records, trace_id):
        if s.name == name and not s.truncated:
            out[s.process].append(s.seconds)
    if skip_first:
        out = {p: ts[int(skip_first):] for p, ts in out.items()}
    return {p: ts for p, ts in out.items() if ts}


def host_step_table(step_times: Dict[int, List[float]]) -> List[dict]:
    """Per-host step-time stats rows (count/total/mean/p50/p95/max),
    sorted by rank — the report table's data."""
    rows = []
    for proc in sorted(step_times):
        times = sorted(step_times[proc])
        if not times:
            continue
        rows.append({
            "process": proc, "steps": len(times),
            "total_s": sum(times),
            "mean_s": sum(times) / len(times),
            "p50_s": _percentile(times, 0.50),
            "p95_s": _percentile(times, 0.95),
            "max_s": times[-1],
        })
    return rows


def _median(vals: Sequence[float]) -> float:
    """Interpolating median (even counts average the middle pair —
    with two hosts, one slow, the nearest-rank median would land
    entirely on one of them and hide the skew)."""
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def straggler_score(step_times: Dict[int, List[float]]
                    ) -> Optional[float]:
    """``max over hosts of p95(host step times), divided by the median
    over hosts of each host's median step time`` — the slowest host's
    tail against the TYPICAL host's typical step.  Lower is better,
    ~1.0 balanced; None without samples or with a zero denominator (a
    degenerate all-instant run has no skew to score)."""
    per_host = [ts for ts in step_times.values() if ts]
    if not per_host:
        return None
    med = _median([_median(ts) for ts in per_host])
    if med <= 0:
        return None
    worst = max(_percentile(sorted(ts), 0.95) for ts in per_host)
    return worst / med


def slowest_host(step_times: Dict[int, List[float]]) -> Optional[int]:
    """The rank with the highest p95 step time (None without
    samples)."""
    best = None
    for proc, ts in step_times.items():
        if not ts:
            continue
        p95 = _percentile(sorted(ts), 0.95)
        if best is None or p95 > best[1]:
            best = (proc, p95)
    return None if best is None else best[0]


def critical_path(root: Span) -> List[Span]:
    """Root→leaf chain following the child whose subtree ends last
    (ties and missing timestamps fall back to the longest child) — the
    chain of work that bounded the wall clock."""
    path = [root]
    node = root
    while node.children:
        def _key(c: Span):
            e = c.end()
            return (e is not None, e if e is not None else c.seconds,
                    c.seconds)
        node = max(node.children, key=_key)
        path.append(node)
    return path


def critical_path_host(path: Sequence[Span]) -> Optional[int]:
    """The host the critical path attributes the time to: the rank
    owning the most closed-span seconds along the path below the root
    (falling back to the deepest span's rank when nothing closed —
    e.g. a path of truncated spans)."""
    if len(path) < 2:
        return path[0].process if path else None
    per: Dict[int, float] = defaultdict(float)
    for s in path[1:]:
        if not s.truncated:
            per[s.process] += s.seconds
    if per:
        return max(per.items(), key=lambda kv: kv[1])[0]
    return path[-1].process


@dataclasses.dataclass
class TraceReport:
    """One trace's analysis — what :func:`analyze` returns and what a
    ``trace_summary`` record serializes."""

    trace_id: str
    spans: int
    hosts: List[int]
    roots: int
    orphans: int
    truncated: int
    connected: bool
    critical_path: List[Span]
    critical_path_s: Optional[float]
    critical_host: Optional[int]
    step_times: Dict[int, List[float]]
    straggler_score: Optional[float]
    slowest_host: Optional[int]

    def summary_fields(self) -> dict:
        """The ``trace_summary`` record's field set (pass to
        ``Telemetry.trace_summary(**report.summary_fields())``)."""
        out = {
            "trace_id": self.trace_id, "spans": int(self.spans),
            "hosts": len(self.hosts), "roots": int(self.roots),
            "truncated": int(self.truncated),
            "connected": bool(self.connected),
            "critical_path": [
                {"name": s.name, "process": int(s.process),
                 "seconds": round(float(s.seconds), 6),
                 "truncated": bool(s.truncated)}
                for s in self.critical_path],
        }
        if self.critical_path_s is not None:
            out["critical_path_s"] = round(float(self.critical_path_s),
                                           6)
        if self.straggler_score is not None:
            out["straggler_score"] = round(float(self.straggler_score),
                                           4)
        return out


def analyze(records: Sequence[dict],
            trace_id: Optional[str] = None, *,
            step_span: str = STEP_SPAN_NAME,
            skip_first: int = 0) -> Optional[TraceReport]:
    """Analyze one trace of ``records`` (the only one present, or the
    one named).  None when no traced spans match.  With several roots
    (a stream missing its cross-process root record) the critical path
    starts from the root whose subtree ends last."""
    if trace_id is None:
        ids = trace_ids(records)
        if not ids:
            return None
        trace_id = ids[0]
    spans = collect_spans(records, trace_id)
    if not spans:
        return None
    roots, orphans = build_forest(spans)
    def _root_key(r: Span):
        e = r.end()
        return (e is not None, e if e is not None else r.seconds)
    start = max(roots, key=_root_key)
    path = critical_path(start)
    closed = [s for s in path if not s.truncated]
    path_s = sum(s.seconds for s in closed[1:]) if len(closed) > 1 \
        else (closed[0].seconds if closed else None)
    steps = per_host_step_times(records, name=step_span,
                                trace_id=trace_id,
                                skip_first=skip_first)
    return TraceReport(
        trace_id=trace_id, spans=len(spans), hosts=hosts_of(spans),
        roots=len(roots), orphans=orphans,
        truncated=sum(1 for s in spans if s.truncated),
        connected=(len(roots) == 1 and orphans == 0),
        critical_path=path, critical_path_s=path_s,
        critical_host=critical_path_host(path),
        step_times=steps, straggler_score=straggler_score(steps),
        slowest_host=slowest_host(steps))


def render_tree(roots: Sequence[Span], *, max_depth: int = 12,
                max_children: int = 16) -> str:
    """Indented text rendering of a span forest (the CLI's -v view)."""
    lines: List[str] = []

    def walk(span: Span, depth: int):
        mark = " TRUNCATED" if span.truncated else ""
        dur = "?" if span.truncated else f"{span.seconds * 1e3:.1f}ms"
        lines.append(f"{'  ' * depth}{span.name} "
                     f"[h{span.process}] {dur}{mark}")
        if depth >= max_depth:
            return
        for i, c in enumerate(span.children):
            if i >= max_children:
                lines.append(f"{'  ' * (depth + 1)}"
                             f"… {len(span.children) - i} more")
                break
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


def to_chrome_trace(records: Sequence[dict],
                    trace_id: Optional[str] = None) -> dict:
    """Chrome trace-event JSON (the ``chrome://tracing`` / Perfetto
    format): one complete ("ph":"X") event per span, ``pid`` = host
    rank, spans without wall-clock anchors laid out back-to-back.
    Truncated spans get their effective end (deepest descendant) and
    ``args.truncated`` so the kill is visible as a clipped box."""
    spans = collect_spans(records, trace_id)
    build_forest(spans)
    t0 = min((s.t_start for s in spans if s.t_start is not None),
             default=0.0)
    events: List[dict] = []
    fallback_cursor: Dict[int, float] = defaultdict(float)
    for s in spans:
        if s.t_start is not None:
            ts = (s.t_start - t0) * 1e6
        else:
            ts = fallback_cursor[s.process]
            fallback_cursor[s.process] += max(s.seconds, 1e-6) * 1e6
        if s.truncated:
            end = s.end()
            dur = max(((end - t0) * 1e6 - ts)
                      if (end is not None and s.t_start is not None)
                      else 1.0, 1.0)
        else:
            dur = max(s.seconds * 1e6, 1.0)
        args = {"span_id": s.span_id, "parent_id": s.parent_id,
                "status": s.status, "trace_id": s.trace_id}
        if s.truncated:
            args["truncated"] = True
        events.append({"name": s.name, "cat": "span", "ph": "X",
                       "ts": round(ts, 3), "dur": round(dur, 3),
                       "pid": s.process, "tid": 0, "args": args})
    for p in sorted({s.process for s in spans}):
        events.append({"name": "process_name", "ph": "M", "pid": p,
                       "tid": 0, "args": {"name": f"host {p}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
