"""The canonical run-record JSONL schema.

Before this module every producer serialized its own incompatible JSON:
``benchmarks/run.py`` emitted bare config records, ``bench.py`` emitted
its ladder/bank shapes, and ``utils/logging.py`` emitted ad-hoc
per-iteration dicts — three artifact families no one tool could read.
This module defines ONE record family every producer stamps and every
consumer (``tools/agd_report.py``, future round comparisons of
``BENCH_*`` artifacts) can parse:

- every record carries ``schema_version``, ``kind``, ``run_id``;
- ``kind`` is one of ``run`` (one completed fit/benchmark), ``iteration``
  (one optimizer iteration, live-streamed or post-hoc), ``span`` (one
  timed phase: trace/compile/execute/h2d), ``metrics`` (a registry
  snapshot);
- required and known-optional fields are typed (validated by
  :func:`validate_record`); unknown extra fields are ALLOWED — producers
  keep their tool-specific columns, consumers ignore what they don't
  know.  Existing artifact readers (e.g. ``bench.py``'s replay path)
  keep working because stamping only ADDS keys.

Deliberately dependency-free (stdlib only): ``bench.py`` stamps its
one-line contract through here and must never grow a heavy import, and
``python -m spark_agd_tpu.obs --selfcheck`` validates an example record
in CI without touching a backend.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Tuple

SCHEMA_VERSION = 1

KINDS = ("run", "iteration", "span", "metrics", "program_cost",
         "numerics_failure", "attempt", "recovery", "heartbeat",
         "chaos", "journal_replay", "degraded", "contract_pin",
         "serve_request", "serve_latency", "trace_summary",
         "scaling_curve", "skew_estimate", "rebalance",
         "canary", "promotion", "fleet_route", "replica_verdict",
         "shard_quarantine", "stream_epoch")

# the recovery actions the resilience layer emits; validation accepts
# any string (producers may grow new actions), this tuple documents the
# canonical set for consumers.  ``hot_swap`` is the serving registry's
# generation swap (serve.registry); ``flight_dump`` records a flight-
# recorder dump written by a failure path (obs.flight); ``rebalance``
# and ``speculative_exec`` are the straggler scheduler's actions
# (resilience.scheduler); ``rollback_generation`` is the continuous-
# learning pipeline repointing serving HEAD back to the prior
# generation after a failed promotion (pipeline.promote);
# ``replica_evict``/``request_hedge``/``request_retry`` are the fleet
# router's actions (serve.router): a LOST replica removed from the
# candidate set, a tail request re-issued to a second replica, and an
# in-flight request transparently re-served on a survivor;
# ``native_fallback`` is the one-time typed record of the data plane
# dropping to the Python parser because the native .so is missing or
# ABI-mismatched (native/__init__.py); ``stream_resume`` records a
# streamed pass resuming mid-epoch from a persisted StreamCursor
# (data.streaming.StreamCheckpoint).
RECOVERY_ACTIONS = ("retry", "rollback", "preemption_flush",
                    "checkpoint", "checkpoint_fallback", "resume",
                    "host_lost", "elastic_resume", "degraded_continue",
                    "hot_swap", "flight_dump", "rebalance",
                    "speculative_exec", "rollback_generation",
                    "replica_evict", "request_hedge", "request_retry",
                    "native_fallback", "stream_resume")

_NUM = (int, float)
_OPT_NUM = _NUM + (type(None),)

# kind -> {field: allowed types}; None in a tuple permits JSON null
_REQUIRED: Dict[str, dict] = {
    "run": {"run_id": str, "tool": str, "timestamp_unix": _NUM},
    "iteration": {"run_id": str, "algorithm": str, "iter": int,
                  "loss": _NUM},
    "span": {"run_id": str, "name": str, "seconds": _NUM},
    "metrics": {"run_id": str, "metrics": dict},
    # one compiled program's cost/memory/collective accounting
    # (obs.introspect.ProgramCost); ``label`` is the pairing key the
    # perf gate matches baseline/candidate programs on
    "program_cost": {"run_id": str, "label": str, "collectives": dict},
    # a sanitizer hit (utils.debug) or an in-loop non-finite loss,
    # landed in the same JSONL as the metrics it poisoned
    "numerics_failure": {"run_id": str, "message": str},
    # one supervised fit attempt (resilience.supervisor): outcome is
    # "ok" | "failed" | "aborted_non_finite"
    "attempt": {"run_id": str, "attempt": int, "outcome": str},
    # one recovery action (resilience layer): action is one of
    # RECOVERY_ACTIONS (open set — consumers ignore unknown actions)
    "recovery": {"run_id": str, "action": str},
    # one liveness beat of one SPMD process (resilience.distributed.
    # HeartbeatWriter); ``process`` is the jax process index — the
    # host-loss monitor reads staleness from these
    "heartbeat": {"run_id": str, "process": int},
    # one injected fault of a chaos campaign (resilience.chaos);
    # ``fault`` is the kind (chaos.FAULT_KINDS — open set)
    "chaos": {"run_id": str, "fault": str},
    # one recovery-journal replay/repair (resilience.journal.Journal):
    # ``records`` committed records recovered from the WAL
    "journal_replay": {"run_id": str, "records": int},
    # one quorum-gated degraded continuation (resilience.degrade):
    # ``surviving`` processes keep training without their dead peers
    "degraded": {"run_id": str, "surviving": int},
    # one compiled-program contract check (analysis.contracts):
    # ``contract`` is constant-bytes / donation / collective-census,
    # ``ok`` whether the pin held against the real XLA program
    "contract_pin": {"run_id": str, "contract": str, "ok": bool},
    # one inference request through the serving plane (serve.queue):
    # ``rows`` is the request's row count; ``status`` ok/rejected/error
    "serve_request": {"run_id": str, "rows": int},
    # one serving-latency rollup (serve.queue.latency_summary):
    # ``requests`` completed in the window; QPS and percentile fields
    # ride as optionals
    "serve_latency": {"run_id": str, "requests": int},
    # one trace's analysis rollup (obs.timeline.analyze): ``spans``
    # reconstructed span count; hosts/critical path/straggler score
    # ride as optionals
    "trace_summary": {"run_id": str, "trace_id": str, "spans": int},
    # one weak-scaling ladder (obs.scaling / benchmarks.run.run_ladder):
    # ``points`` is the ordered per-mesh-shape measurement list (each a
    # dict with devices/wall/sec_per_iter/program cost/contention);
    # efficiency, serial fraction, and the environment fingerprint ride
    # as optionals — the record family obs.perfgate gates on curve
    # SHAPE, not single numbers
    "scaling_curve": {"run_id": str, "name": str, "points": list},
    # one skew sync of the straggler scheduler (resilience.scheduler.
    # SkewTracker): ``skew`` is max per-host boundary cost over the
    # median (1.0 balanced); speeds/straggler/hysteresis ride as
    # optionals
    "skew_estimate": {"run_id": str, "skew": _NUM},
    # one applied generation-boundary rebalance decision (resilience.
    # scheduler.StragglerScheduler): ``at_iter`` is the boundary it was
    # decided at; the before/after per-host partition counts ride as
    # optionals
    "rebalance": {"run_id": str, "at_iter": int},
    # one shadow-served canary evaluation of a candidate generation
    # (pipeline.canary): ``generation`` is the candidate, ``verdict``
    # is "pass" | "fail" | "refused"; slice fraction, quality delta,
    # and per-leg latency evidence ride as optionals
    "canary": {"run_id": str, "generation": int, "verdict": str},
    # one typed promotion decision (pipeline.promote): ``decision`` is
    # "promoted" | "rejected" | "rolled_back"; from/to generation and
    # the gate evidence ride as optionals
    "promotion": {"run_id": str, "decision": str},
    # one routing decision of the serve fleet router (serve.router):
    # ``decision`` is "route" | "hedge" | "retry" | "shed_tenant";
    # replica/tenant/latency evidence rides as optionals
    "fleet_route": {"run_id": str, "decision": str},
    # one replica-health classification change (serve.router, from
    # HostMonitor.verdicts()): ``verdict`` is "ok" | "slow" | "lost"
    "replica_verdict": {"run_id": str, "replica": int,
                        "verdict": str},
    # one poisoned-shard quarantine decision (data.streaming.
    # StreamingDataset): ``shard`` names the part that failed parse/
    # validation/CRC after its retries; the streamed epoch continues
    # degraded on the survivors — the data-plane analogue of
    # resilience.degrade
    "shard_quarantine": {"run_id": str, "shard": str},
    # one completed streamed pass over a StreamingDataset
    # (data.streaming.make_streaming_smooth): ``epoch`` is the pass
    # ordinal, ``batches`` how many macro-batches the fold consumed;
    # stall/overlap evidence rides as optionals — the record family
    # obs.perfgate.gate_stream bounds prefetch stall fraction on
    "stream_epoch": {"run_id": str, "epoch": int, "batches": int},
}

# JSON value types the contract-pin observed/expected fields may carry
_JSON_VAL = (int, float, str, dict, list, bool, type(None))

_OPTIONAL: Dict[str, dict] = {
    "run": {
        "algorithm": str, "name": str, "platform": str,
        "device_kind": str, "n_devices": int, "iters": int,
        "final_loss": _OPT_NUM, "converged": bool,
        "iters_per_sec": _OPT_NUM,
        "wall_s": _NUM, "compile_s": _NUM,
        "error": (str, type(None)), "metrics": dict,
        # environment provenance (obs.introspect.environment_
        # fingerprint) — the fields the perf gate refuses to compare
        # across
        "jax_version": str, "jaxlib_version": str,
        "n_processes": int, "mesh_shape": dict,
        # serving soak summaries (tools/serve_drill.py): the fields the
        # perf gate's latency metrics pair on
        "requests": int, "rejected": int, "hot_swaps": int,
        "qps": _OPT_NUM, "p50_ms": _OPT_NUM, "p99_ms": _OPT_NUM,
        # per-host skew (obs.timeline.straggler_score over the run's
        # trace): the perf gate's lower-is-better skew metric
        "straggler_score": _OPT_NUM, "hosts": int,
        # hardened host-environment provenance (obs.scaling.
        # host_fingerprint, merged into environment_fingerprint):
        # identity fields enter the history env_key; loadavg_1m is
        # measurement-time state for the contention sentinel
        "cpu_count": (int, type(None)), "loadavg_1m": _NUM,
        "cpu_governor": str, "cpu_turbo": str,
        "cgroup_cpu_quota": (_NUM + (str,)), "env_key": str,
        # which weight-update execution mode the run used:
        # "replicated" (full update everywhere) or "sharded"
        # (reduce-scatter → 1/N prox → allgather,
        # parallel.sharded_update)
        "update_mode": str,
    },
    "iteration": {"L": _NUM, "theta": _NUM, "step": _NUM,
                  "restarted": bool, "accepted": bool,
                  "timestamp_unix": _NUM},
    # the trace fields (obs.trace) are OPTIONAL: untraced phase spans
    # carry none of them; a traced span carries all of trace_id/
    # span_id/process/status/t_start_unix (parent_id None at a root).
    # ``status`` is "open" for the flushed start marker, then "ok"/
    # "error" (or a producer status) on the closing record — an "open"
    # with no close is a TRUNCATED span (the emitting host died).
    "span": {"timestamp_unix": _NUM, "trace_id": str, "span_id": str,
             "parent_id": (str, type(None)), "process": int,
             "status": str, "t_start_unix": _NUM,
             "error": (str, type(None)), "tool": str},
    "metrics": {"timestamp_unix": _NUM, "tool": str},
    "program_cost": {
        "flops": _OPT_NUM, "transcendentals": _OPT_NUM,
        "bytes_accessed": _OPT_NUM,
        "argument_bytes": _OPT_NUM, "output_bytes": _OPT_NUM,
        "temp_bytes": _OPT_NUM, "alias_bytes": _OPT_NUM,
        "generated_code_bytes": _OPT_NUM, "peak_hbm_bytes": _OPT_NUM,
        "hlo_bytes": int, "backend": str, "algorithm": str,
        # per-collective result bytes (obs.introspect.collective_bytes):
        # the all-reduce-bytes-collapse signature of the sharded update
        "collective_bytes": (dict, type(None)),
        "tool": str, "timestamp_unix": _NUM,
    },
    "numerics_failure": {
        "leaf": (str, type(None)), "iter": int, "evaluation": int,
        "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "attempt": {
        "start_iter": int, "iters": int, "seconds": _NUM,
        "error": (str, type(None)),
        "failure_kind": (str, type(None)), "algorithm": str,
        "tool": str, "timestamp_unix": _NUM,
    },
    "recovery": {
        "reason": str, "failure_kind": str, "attempt": int,
        "backoff_s": _NUM, "from_iter": int, "to_iter": int,
        "big_l": _NUM, "path": str, "generation": int,
        "process": int, "process_count": int, "saved_process_count": int,
        # the speculative_exec action's accounting (resilience.
        # scheduler.resolve_speculation)
        "outcome": str, "matched": bool, "iters": int,
        "seconds": _NUM, "fleet_seconds": _NUM, "max_diff": _NUM,
        "straggler": int,
        "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "heartbeat": {
        "process_count": int, "iter": int, "phase": str, "pid": int,
        "algorithm": str, "tool": str, "timestamp_unix": _NUM,
    },
    "chaos": {
        "at_iter": int, "fired_iter": int,
        "process": (int, type(None)), "seed": int,
        "campaign": (int, str), "payload": _NUM, "outcome": str,
        "algorithm": str, "tool": str, "timestamp_unix": _NUM,
    },
    "journal_replay": {
        "path": str, "torn_bytes": int, "last_seq": int,
        "repaired": bool, "reason": (str, type(None)),
        "tool": str, "timestamp_unix": _NUM,
    },
    "degraded": {
        "saved_process_count": int, "lost": list, "quorum": _NUM,
        "min_quorum": _NUM, "generation": int, "to_iter": int,
        "process": int, "dropped_partitions": int, "source": str,
        "tool": str, "timestamp_unix": _NUM,
    },
    "contract_pin": {
        "label": str, "message": str, "observed": _JSON_VAL,
        "expected": _JSON_VAL, "budget_bytes": int, "algorithm": str,
        "tool": str, "timestamp_unix": _NUM,
    },
    "serve_request": {
        "op": str, "status": str, "bucket": int, "batch_rows": int,
        "queue_ms": _NUM, "latency_ms": _NUM, "generation": int,
        "model": str, "error": (str, type(None)), "algorithm": str,
        # fleet attribution (serve.router / serve.fleet): which tenant
        # submitted the request and which replica served it
        "tenant": str, "replica": int,
        "tool": str, "timestamp_unix": _NUM,
    },
    "serve_latency": {
        "rows": int, "qps": _OPT_NUM, "p50_ms": _OPT_NUM,
        "p99_ms": _OPT_NUM, "mean_ms": _OPT_NUM, "max_ms": _OPT_NUM,
        "queue_depth": int, "rejected": int, "errors": int,
        "hot_swaps": int, "generation": int, "window_s": _NUM,
        # which replica's latency ring the rollup summarizes — the
        # attribution the router's EWMA pairs its numbers against
        "replica": int,
        "model": str, "tool": str, "timestamp_unix": _NUM,
    },
    "trace_summary": {
        "hosts": int, "roots": int, "truncated": int,
        "connected": bool, "critical_path_s": _OPT_NUM,
        "critical_path": list, "straggler_score": _OPT_NUM,
        "slowest_host": (int, type(None)), "step_span": str,
        "algorithm": str, "tool": str, "timestamp_unix": _NUM,
    },
    "scaling_curve": {
        "n_points": int, "max_devices": int, "efficiency": list,
        "serial_fraction": _OPT_NUM, "contention_flagged": int,
        "rows_per_device": int, "iters": int, "ladder": str,
        "spin_baseline_s": _NUM, "env_key": str,
        # the environment fingerprint rides flat so the gate's refusal
        # logic reads curves and runs identically
        "platform": str, "device_kind": str, "n_devices": int,
        "jax_version": str, "jaxlib_version": str, "n_processes": int,
        "mesh_shape": dict, "cpu_count": (int, type(None)),
        "loadavg_1m": _NUM, "cpu_governor": str, "cpu_turbo": str,
        "cgroup_cpu_quota": (_NUM + (str,)),
        # the update-mode gate (obs.perfgate.gate_update_modes) pairs
        # replicated-vs-sharded curves on this field
        "update_mode": str,
        "algorithm": str, "tool": str, "timestamp_unix": _NUM,
    },
    "skew_estimate": {
        "speeds": dict, "straggler": (int, type(None)),
        "consecutive": int, "persistent": bool, "iter": int,
        "window_segments": int, "threshold": _NUM,
        "hb_slow": list, "process": int, "source": str,
        "algorithm": str, "tool": str, "timestamp_unix": _NUM,
    },
    "rebalance": {
        "speeds": dict, "skew": _NUM, "straggler": (int, type(None)),
        "before": dict, "after": dict, "moved": int,
        "generation": int, "process": int, "reason": str,
        "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "canary": {
        # which generation the candidate shadowed, and what fraction of
        # live traffic was mirrored to it
        "baseline_generation": int, "slice_fraction": _NUM,
        "shadow_requests": int, "epoch": int,
        # quality leg: held-out loss of baseline vs candidate
        # (models.evaluation.log_loss) and the relative threshold the
        # gate applied
        "quality_baseline": _OPT_NUM, "quality_candidate": _OPT_NUM,
        "quality_delta": _OPT_NUM, "quality_threshold": _NUM,
        "quality_verdict": str, "quality_fault_injected": bool,
        # latency leg: candidate shadow percentiles vs HEAD's
        "p50_ms": _OPT_NUM, "p99_ms": _OPT_NUM,
        "baseline_p50_ms": _OPT_NUM, "baseline_p99_ms": _OPT_NUM,
        "latency_verdict": str, "contention_flagged": bool,
        # refusal evidence (spec mismatch, torn target, thin traffic)
        "refusals": list, "baseline_spec": dict, "candidate_spec": dict,
        "reason": str, "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "promotion": {
        "from_generation": (int, type(None)), "to_generation": int,
        "candidate_generation": int, "epoch": int,
        # the gate evidence the decision was made on: the canary
        # verdict, perfgate status, and any refusal strings
        "gate_status": str, "evidence": dict, "refusals": list,
        "reason": str, "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "fleet_route": {
        # the replica the decision targeted (for hedges: the SECOND
        # replica the request was re-issued to; ``winner`` which one
        # answered first)
        "replica": int, "winner": (int, type(None)),
        "op": str, "tenant": str, "rows": int, "attempt": int,
        # the evidence the decision was made on: the request's elapsed
        # latency, the replica's EWMA estimate, the fleet median, the
        # replica's outstanding in-flight count, and its verdict
        "latency_ms": _NUM, "ewma_ms": _OPT_NUM, "median_ms": _OPT_NUM,
        "outstanding": int, "verdict": str, "generation": int,
        "error": (str, type(None)), "reason": str,
        "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "replica_verdict": {
        # staleness/phase evidence behind the classification, and the
        # verdict it transitioned from (absent on the first sighting)
        "age_s": _OPT_NUM, "phase": (str, type(None)),
        "previous": (str, type(None)), "generation": int,
        "source": str, "tool": str, "timestamp_unix": _NUM,
    },
    "shard_quarantine": {
        # why the shard was expelled, how many read attempts it got,
        # and the surviving data fraction the policy judged
        "reason": str, "attempts": int, "shard_index": int,
        "rows_lost": (int, type(None)), "healthy": int, "total": int,
        "data_fraction": _NUM, "epoch": int,
        "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
    "stream_epoch": {
        # pass accounting: rows folded, wall time of the pass, and the
        # consumer-side prefetch stall it spent waiting on the reader
        "rows": int, "pass_s": _NUM, "stall_s": _NUM,
        "stall_fraction": _NUM,
        # resume evidence: the batch index a StreamCursor restarted the
        # pass from (None/absent on an uninterrupted pass)
        "resumed_from_batch": (int, type(None)), "skipped_batches": int,
        "quarantined": int, "prefetch": int,
        "contention_flagged": bool,
        "source": str, "algorithm": str, "tool": str,
        "timestamp_unix": _NUM,
    },
}

_run_counter = itertools.count()


def new_run_id() -> str:
    """Process-unique, time-sortable id: ms timestamp + pid + counter."""
    return (f"r{int(time.time() * 1000):x}"
            f"-{os.getpid():x}-{next(_run_counter):x}")


def _type_ok(value, types) -> bool:
    if not isinstance(types, tuple):
        types = (types,)
    # bool is an int subclass in Python; an int-typed field (e.g.
    # ``iter``) must not silently accept True
    if isinstance(value, bool):
        return bool in types
    # a float-typed field accepts ints (JSON has one number type)
    return isinstance(value, types)


def validate_record(rec) -> List[str]:
    """Errors for one record against the schema; ``[]`` means valid.

    Checks the canonical keys and the typed known-optional keys; extra
    unknown keys are allowed by design (see module docstring).
    """
    errors: List[str] = []
    if not isinstance(rec, dict):
        return [f"record must be a dict, got {type(rec).__name__}"]
    sv = rec.get("schema_version")
    if sv != SCHEMA_VERSION:
        errors.append(f"schema_version must be {SCHEMA_VERSION}, "
                      f"got {sv!r}")
    kind = rec.get("kind")
    if kind not in KINDS:
        errors.append(f"kind must be one of {KINDS}, got {kind!r}")
        return errors
    for field, types in _REQUIRED[kind].items():
        if field not in rec:
            errors.append(f"{kind} record missing required field "
                          f"{field!r}")
        elif not _type_ok(rec[field], types):
            errors.append(
                f"{field!r} must be "
                f"{getattr(types, '__name__', types)}, got "
                f"{type(rec[field]).__name__}")
    for field, types in _OPTIONAL[kind].items():
        if field in rec and not _type_ok(rec[field], types):
            errors.append(
                f"{field!r} must be "
                f"{getattr(types, '__name__', types)}, got "
                f"{type(rec[field]).__name__}")
    if kind == "iteration" and isinstance(rec.get("iter"), int) \
            and rec["iter"] < 1:
        errors.append("iter is 1-based (the reference's nIter); got "
                      f"{rec['iter']}")
    return errors


def stamp(rec: dict, *, tool: str, kind: str = "run",
          run_id: Optional[str] = None) -> dict:
    """A COPY of ``rec`` with the canonical fields added (existing keys
    are never overwritten, so re-stamping and legacy producers with
    their own ``run_id`` are both safe)."""
    out = dict(rec)
    out.setdefault("schema_version", SCHEMA_VERSION)
    out.setdefault("kind", kind)
    out.setdefault("run_id", run_id or new_run_id())
    out.setdefault("tool", tool)
    out.setdefault("timestamp_unix", round(time.time(), 3))
    return out


def run_record(*, tool: str, run_id: Optional[str] = None,
               **fields) -> dict:
    return stamp(fields, tool=tool, kind="run", run_id=run_id)


def iteration_record(run_id: str, algorithm: str, it: int,
                     **fields) -> dict:
    return {"schema_version": SCHEMA_VERSION, "kind": "iteration",
            "run_id": run_id, "algorithm": algorithm, "iter": int(it),
            **fields}


def span_record(run_id: str, name: str, seconds: float) -> dict:
    return {"schema_version": SCHEMA_VERSION, "kind": "span",
            "run_id": run_id, "name": name,
            "seconds": float(seconds)}


def metrics_record(run_id: str, metrics: dict, *,
                   tool: Optional[str] = None) -> dict:
    rec = {"schema_version": SCHEMA_VERSION, "kind": "metrics",
           "run_id": run_id, "metrics": dict(metrics)}
    if tool is not None:
        rec["tool"] = tool
    return rec


def program_cost_record(run_id: str, label: str, collectives: dict,
                        **fields) -> dict:
    """One compiled program's cost accounting; ``collectives`` maps
    collective op name -> count (``obs.introspect.collective_census``)."""
    return {"schema_version": SCHEMA_VERSION, "kind": "program_cost",
            "run_id": run_id, "label": label,
            "collectives": dict(collectives), **fields}


def numerics_failure_record(run_id: str, message: str,
                            **fields) -> dict:
    """A non-finite hit: ``leaf`` names the first failing quantity when
    known, ``iter``/``evaluation`` locate it in the run."""
    return {"schema_version": SCHEMA_VERSION, "kind": "numerics_failure",
            "run_id": run_id, "message": message, **fields}


def attempt_record(run_id: str, attempt: int, outcome: str,
                   **fields) -> dict:
    """One supervised fit attempt (``resilience.supervisor``):
    ``outcome`` is ``ok`` / ``failed`` / ``aborted_non_finite``;
    ``start_iter``/``iters``/``seconds``/``error``/``failure_kind``
    locate and explain it."""
    return {"schema_version": SCHEMA_VERSION, "kind": "attempt",
            "run_id": run_id, "attempt": int(attempt),
            "outcome": str(outcome), **fields}


def recovery_record(run_id: str, action: str, **fields) -> dict:
    """One recovery action of the resilience layer — ``action`` is one
    of :data:`RECOVERY_ACTIONS` (retry, rollback, preemption_flush,
    checkpoint, checkpoint_fallback, resume, host_lost,
    elastic_resume)."""
    return {"schema_version": SCHEMA_VERSION, "kind": "recovery",
            "run_id": run_id, "action": str(action), **fields}


def heartbeat_record(run_id: str, process: int, **fields) -> dict:
    """One liveness beat of one SPMD process — ``process`` is the jax
    process index; ``iter``/``phase`` locate the beat in the run, and
    the host-loss monitor derives staleness from ``timestamp_unix``."""
    return {"schema_version": SCHEMA_VERSION, "kind": "heartbeat",
            "run_id": run_id, "process": int(process), **fields}


def chaos_record(run_id: str, fault: str, **fields) -> dict:
    """One injected fault of a chaos campaign (``resilience.chaos``) —
    ``fault`` names the kind, ``at_iter``/``fired_iter`` locate the
    scripted vs actual firing boundary, ``seed`` ties the record to its
    deterministic campaign."""
    return {"schema_version": SCHEMA_VERSION, "kind": "chaos",
            "run_id": run_id, "fault": str(fault), **fields}


def journal_replay_record(run_id: str, records: int, **fields) -> dict:
    """One recovery-journal replay (``resilience.journal``): how many
    committed records were recovered, ``torn_bytes`` dropped from the
    tail, and whether the file was repaired in place."""
    return {"schema_version": SCHEMA_VERSION, "kind": "journal_replay",
            "run_id": run_id, "records": int(records), **fields}


def degraded_record(run_id: str, surviving: int, **fields) -> dict:
    """One quorum-gated degraded continuation (``resilience.degrade``):
    ``surviving`` of ``saved_process_count`` processes keep training on
    the surviving data partitions (``dropped_partitions`` lost with the
    dead hosts)."""
    return {"schema_version": SCHEMA_VERSION, "kind": "degraded",
            "run_id": run_id, "surviving": int(surviving), **fields}


def contract_pin_record(run_id: str, contract: str, ok: bool,
                        **fields) -> dict:
    """One compiled-program contract check (``analysis.contracts``):
    ``contract`` names the pin (constant-bytes / donation /
    collective-census), ``ok`` whether it held; ``label`` names the
    program, ``observed``/``expected`` carry the mismatch."""
    return {"schema_version": SCHEMA_VERSION, "kind": "contract_pin",
            "run_id": run_id, "contract": str(contract),
            "ok": bool(ok), **fields}


def serve_request_record(run_id: str, rows: int, **fields) -> dict:
    """One inference request through the serving plane
    (``serve.queue``): ``rows`` the request's row count, ``status``
    ok/rejected/error, ``bucket``/``batch_rows`` the padded shape and
    coalesced batch it rode in, ``generation`` the model generation
    that served it."""
    return {"schema_version": SCHEMA_VERSION, "kind": "serve_request",
            "run_id": run_id, "rows": int(rows), **fields}


def serve_latency_record(run_id: str, requests: int, **fields) -> dict:
    """One serving-latency rollup (``serve.queue.latency_summary``):
    ``requests`` completed in the window, with QPS, p50/p99/mean/max
    latency, queue depth, reject/error counts, and the hot-swap census
    as optional fields."""
    return {"schema_version": SCHEMA_VERSION, "kind": "serve_latency",
            "run_id": run_id, "requests": int(requests), **fields}


def trace_summary_record(run_id: str, trace_id: str, spans: int,
                         **fields) -> dict:
    """One trace's analysis rollup (``obs.timeline.analyze``):
    ``spans`` reconstructed, with host/truncation counts, the critical
    path, and the straggler score as optional fields — the record the
    drills pin their causal-tree acceptance on."""
    return {"schema_version": SCHEMA_VERSION, "kind": "trace_summary",
            "run_id": run_id, "trace_id": str(trace_id),
            "spans": int(spans), **fields}


def scaling_curve_record(run_id: str, name: str, points: list,
                         **fields) -> dict:
    """One weak-scaling ladder (``obs.scaling`` + ``benchmarks.run.
    run_ladder``): ``points`` is the ordered per-mesh-shape measurement
    list; efficiency/serial-fraction/contention and the environment
    fingerprint ride as optional fields — what ``obs.perfgate.
    gate_scaling`` gates on curve shape."""
    return {"schema_version": SCHEMA_VERSION, "kind": "scaling_curve",
            "run_id": run_id, "name": str(name),
            "points": list(points), **fields}


def skew_estimate_record(run_id: str, skew: float, **fields) -> dict:
    """One skew sync of the straggler scheduler
    (``resilience.scheduler``): ``skew`` is the max per-host boundary
    cost over the fleet median (1.0 = balanced); ``speeds`` the
    relative per-host estimates, ``straggler``/``consecutive``/
    ``persistent`` the hysteresis state."""
    return {"schema_version": SCHEMA_VERSION, "kind": "skew_estimate",
            "run_id": run_id, "skew": float(skew), **fields}


def rebalance_record(run_id: str, at_iter: int, **fields) -> dict:
    """One applied generation-boundary rebalance
    (``resilience.scheduler``): ``at_iter`` the boundary it was decided
    at; ``before``/``after`` the per-host partition counts, ``moved``
    how many partitions changed hands, ``generation`` the manifest
    generation the new assignment commits under."""
    return {"schema_version": SCHEMA_VERSION, "kind": "rebalance",
            "run_id": run_id, "at_iter": int(at_iter), **fields}


def canary_record(run_id: str, generation: int, verdict: str,
                  **fields) -> dict:
    """One shadow-served canary evaluation (``pipeline.canary``):
    ``generation`` is the candidate, ``verdict`` pass/fail/refused;
    ``slice_fraction``/``shadow_requests`` size the shadow leg,
    ``quality_*`` and ``p50_ms``/``p99_ms`` carry the two gate legs'
    evidence, ``refusals`` why the gate refused to judge."""
    return {"schema_version": SCHEMA_VERSION, "kind": "canary",
            "run_id": run_id, "generation": int(generation),
            "verdict": str(verdict), **fields}


def promotion_record(run_id: str, decision: str, **fields) -> dict:
    """One typed promotion decision (``pipeline.promote``):
    ``decision`` is promoted/rejected/rolled_back;
    ``from_generation``/``to_generation`` the HEAD movement,
    ``evidence`` the canary/gate record the decision rode on."""
    return {"schema_version": SCHEMA_VERSION, "kind": "promotion",
            "run_id": run_id, "decision": str(decision), **fields}


def fleet_route_record(run_id: str, decision: str, **fields) -> dict:
    """One routing decision of the serve fleet router
    (``serve.router``): ``decision`` is route/hedge/retry/shed_tenant;
    ``replica``/``tenant``/``op`` locate the request,
    ``latency_ms``/``ewma_ms``/``median_ms``/``outstanding`` carry the
    evidence the router acted on."""
    return {"schema_version": SCHEMA_VERSION, "kind": "fleet_route",
            "run_id": run_id, "decision": str(decision), **fields}


def replica_verdict_record(run_id: str, replica: int, verdict: str,
                           **fields) -> dict:
    """One replica-health classification change (``serve.router``, from
    ``HostMonitor.verdicts()``): ``verdict`` is ok/slow/lost;
    ``age_s``/``phase`` the staleness evidence, ``previous`` the
    verdict it transitioned from."""
    return {"schema_version": SCHEMA_VERSION, "kind": "replica_verdict",
            "run_id": run_id, "replica": int(replica),
            "verdict": str(verdict), **fields}


def shard_quarantine_record(run_id: str, shard: str, **fields) -> dict:
    """One poisoned-shard quarantine decision (``data.streaming``):
    ``shard`` names the part expelled after its read retries;
    ``reason``/``attempts`` explain it, ``healthy``/``total``/
    ``data_fraction`` carry the degraded-continuation evidence the
    minimum-data-fraction policy judged."""
    return {"schema_version": SCHEMA_VERSION, "kind": "shard_quarantine",
            "run_id": run_id, "shard": str(shard), **fields}


def stream_epoch_record(run_id: str, epoch: int, batches: int,
                        **fields) -> dict:
    """One completed streamed pass over a ``StreamingDataset``
    (``data.streaming.make_streaming_smooth``): ``epoch`` is the pass
    ordinal, ``batches`` the macro-batches folded; ``stall_s``/
    ``pass_s``/``stall_fraction`` carry the prefetch-overlap evidence
    ``obs.perfgate.gate_stream`` bounds, ``resumed_from_batch`` the
    StreamCursor resume point when the pass restarted mid-epoch."""
    return {"schema_version": SCHEMA_VERSION, "kind": "stream_epoch",
            "run_id": run_id, "epoch": int(epoch),
            "batches": int(batches), **fields}


def read_jsonl(path: str) -> List[dict]:
    """Parse one record per non-blank line; raises ``ValueError`` naming
    the line on malformed JSON (consumers wanting tolerance — the report
    CLI — catch per line themselves)."""
    out = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not valid JSON: {e}")
    return out


EXAMPLE_RUN_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "run",
    "run_id": "r18c2d3e4-1a2b-0", "tool": "benchmarks.run",
    "timestamp_unix": 1754000000.0, "algorithm": "agd",
    "name": "logistic_l2_rcv1like", "platform": "cpu", "n_devices": 1,
    "iters": 20, "final_loss": 0.3217, "converged": False,
    "iters_per_sec": 412.5, "update_mode": "sharded", "error": None,
}

EXAMPLE_ITERATION_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "iteration",
    "run_id": "r18c2d3e4-1a2b-0", "algorithm": "agd", "iter": 1,
    "loss": 0.6931, "L": 1.0, "theta": 1.0, "step": 1.0,
    "restarted": False,
}

EXAMPLE_SPAN_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "span",
    "run_id": "r18c2d3e4-1a2b-0", "name": "compile", "seconds": 1.25,
    "trace_id": "t9f2ab34c11d0e8a7", "span_id": "s1a2b3c4d5e6f",
    "parent_id": "s0f0e0d0c0b0a", "process": 1, "status": "ok",
    "t_start_unix": 1754000000.0,
}

EXAMPLE_METRICS_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "metrics",
    "run_id": "r18c2d3e4-1a2b-0", "tool": "bench",
    "metrics": {"compile.hits": 3, "compile.misses": 1,
                "resilience.attempts": 1},
    "timestamp_unix": 1754000000.0,
}

EXAMPLE_PROGRAM_COST_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "program_cost",
    "run_id": "r18c2d3e4-1a2b-0", "label": "agd", "algorithm": "agd",
    "flops": 528383.0, "bytes_accessed": 65580.0,
    "argument_bytes": 16384, "output_bytes": 4, "temp_bytes": 16400,
    "peak_hbm_bytes": 32788, "backend": "cpu",
    "collectives": {"all-reduce": 3, "all-gather": 0,
                    "reduce-scatter": 0, "collective-permute": 0,
                    "all-to-all": 0},
    "collective_bytes": {"all-reduce": 96, "all-gather": 0,
                         "reduce-scatter": 0, "collective-permute": 0,
                         "all-to-all": 0},
}

EXAMPLE_NUMERICS_FAILURE_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "numerics_failure",
    "run_id": "r18c2d3e4-1a2b-0",
    "message": "smooth: gradient leaf ['w'] non-finite",
    "leaf": "['w']", "evaluation": 3, "source": "smooth",
}

EXAMPLE_ATTEMPT_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "attempt",
    "run_id": "r18c2d3e4-1a2b-0", "attempt": 2, "outcome": "failed",
    "start_iter": 10, "iters": 0, "seconds": 0.41,
    "error": "SimulatedDeviceLoss: injected device loss at iteration 10",
    "failure_kind": "transient", "algorithm": "agd",
}

EXAMPLE_RECOVERY_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "recovery",
    "run_id": "r18c2d3e4-1a2b-0", "action": "rollback",
    "reason": "non-finite loss in segment", "failure_kind": "numeric",
    "from_iter": 10, "to_iter": 10, "big_l": 64.0,
    "source": "supervisor",
}

EXAMPLE_HEARTBEAT_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "heartbeat",
    "run_id": "r18c2d3e4-1a2b-0", "process": 1, "process_count": 2,
    "iter": 12, "phase": "segment", "pid": 4242,
    "timestamp_unix": 1754000000.0,
}

EXAMPLE_CHAOS_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "chaos",
    "run_id": "r18c2d3e4-1a2b-0", "fault": "device_loss",
    "at_iter": 8, "fired_iter": 8, "process": None, "seed": 17,
}

EXAMPLE_JOURNAL_REPLAY_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "journal_replay",
    "run_id": "r18c2d3e4-1a2b-0", "records": 23,
    "path": "run.journal", "torn_bytes": 11, "last_seq": 22,
    "repaired": True, "reason": "torn payload at byte 2048",
}

EXAMPLE_DEGRADED_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "degraded",
    "run_id": "r18c2d3e4-1a2b-0", "surviving": 1,
    "saved_process_count": 2, "lost": [1], "quorum": 0.5,
    "min_quorum": 0.5, "generation": 3, "to_iter": 12, "process": 0,
    "dropped_partitions": 2, "source": "degrade",
}

EXAMPLE_CONTRACT_PIN_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "contract_pin",
    "run_id": "r18c2d3e4-1a2b-0", "contract": "collective-census",
    "ok": False, "label": "agd",
    "message": "all-reduce: compiled program has 4, pin says 3",
    "observed": {"all-reduce": 4}, "expected": {"all-reduce": 3},
    "tool": "graft_lint",
}

EXAMPLE_SERVE_REQUEST_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "serve_request",
    "run_id": "r18c2d3e4-1a2b-0", "rows": 3, "op": "predict_proba",
    "status": "ok", "bucket": 8, "batch_rows": 7, "generation": 2,
    "queue_ms": 1.8, "latency_ms": 4.2, "tool": "serve.queue",
}

EXAMPLE_TRACE_SUMMARY_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "trace_summary",
    "run_id": "r18c2d3e4-1a2b-0", "trace_id": "t9f2ab34c11d0e8a7",
    "spans": 42, "hosts": 2, "roots": 1, "truncated": 1,
    "connected": True, "critical_path_s": 1.84,
    "critical_path": [{"name": "supervised_run", "process": 0,
                       "seconds": 1.84, "truncated": False}],
    "straggler_score": 1.62, "slowest_host": 0,
    "step_span": "segment", "tool": "agd_trace",
}

EXAMPLE_SERVE_LATENCY_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "serve_latency",
    "run_id": "r18c2d3e4-1a2b-0", "requests": 240, "rows": 1913,
    "qps": 412.5, "p50_ms": 2.1, "p99_ms": 9.7, "mean_ms": 2.9,
    "max_ms": 14.0, "queue_depth": 0, "rejected": 3, "errors": 0,
    "hot_swaps": 1, "generation": 2, "window_s": 0.582,
    "tool": "serve.queue",
}

EXAMPLE_SCALING_CURVE_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "scaling_curve",
    "run_id": "r18c2d3e4-1a2b-0", "name": "logistic_l2_rcv1like",
    "algorithm": "agd", "tool": "benchmarks.run",
    "points": [
        {"devices": 1, "rows": 256, "iters": 8, "wall_s": 0.41,
         "sec_per_iter": 0.0512, "iters_per_sec": 19.5,
         "converged": False, "flops": 528383.0,
         "bytes_accessed": 65580.0, "peak_hbm_bytes": 32788,
         "collectives": {"all-reduce": 0},
         "contention": {"flagged": False, "spin_score": 0.02,
                        "steal_ticks": 0, "loadavg_before": 0.4,
                        "loadavg_during_max": 0.5}},
        {"devices": 2, "rows": 512, "iters": 8, "wall_s": 0.44,
         "sec_per_iter": 0.0550, "iters_per_sec": 18.2,
         "converged": False, "flops": 528383.0,
         "bytes_accessed": 65580.0, "peak_hbm_bytes": 32788,
         "collectives": {"all-reduce": 3},
         "contention": {"flagged": False, "spin_score": 0.03,
                        "steal_ticks": 0, "loadavg_before": 0.5,
                        "loadavg_during_max": 0.5}},
    ],
    "n_points": 2, "max_devices": 2, "efficiency": [1.0, 0.9309],
    "serial_fraction": 0.0742, "contention_flagged": 0,
    "update_mode": "replicated",
    "rows_per_device": 256, "iters": 8, "ladder": "1,2",
    "env_key": "env-9f2ab34c11d0", "platform": "cpu", "n_devices": 8,
    "cpu_count": 8, "loadavg_1m": 0.42, "cgroup_cpu_quota": 8.0,
    "timestamp_unix": 1754000000.0,
}

EXAMPLE_SKEW_ESTIMATE_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "skew_estimate",
    "run_id": "r18c2d3e4-1a2b-0", "skew": 4.82,
    "speeds": {"0": 1.0, "1": 0.21}, "straggler": 1,
    "consecutive": 2, "persistent": False, "iter": 12,
    "window_segments": 1, "threshold": 1.5, "hb_slow": [1],
    "process": 0, "source": "scheduler",
}

EXAMPLE_REBALANCE_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "rebalance",
    "run_id": "r18c2d3e4-1a2b-0", "at_iter": 12,
    "speeds": {"0": 1.0, "1": 0.21}, "skew": 4.82, "straggler": 1,
    "before": {"0": 6, "1": 6}, "after": {"0": 11, "1": 1},
    "moved": 5, "generation": 4, "process": 0,
    "source": "scheduler",
}

EXAMPLE_CANARY_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "canary",
    "run_id": "r18c2d3e4-1a2b-0", "generation": 5, "verdict": "pass",
    "baseline_generation": 4, "slice_fraction": 0.25,
    "shadow_requests": 64, "epoch": 3,
    "quality_baseline": 0.3217, "quality_candidate": 0.3105,
    "quality_delta": -0.0348, "quality_threshold": 0.05,
    "quality_verdict": "pass", "quality_fault_injected": False,
    "p50_ms": 2.4, "p99_ms": 10.1,
    "baseline_p50_ms": 2.1, "baseline_p99_ms": 9.7,
    "latency_verdict": "pass", "contention_flagged": False,
    "refusals": [], "source": "pipeline.canary", "tool": "pipeline",
}

EXAMPLE_PROMOTION_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "promotion",
    "run_id": "r18c2d3e4-1a2b-0", "decision": "rolled_back",
    "from_generation": 5, "to_generation": 4,
    "candidate_generation": 5, "epoch": 3, "gate_status": "failed",
    "evidence": {"verdict": "pass", "post_check": "holdout loss "
                 "regressed 412% after repoint"},
    "refusals": [], "reason": "post-promotion quality check failed",
    "source": "pipeline.promote", "tool": "pipeline",
}

EXAMPLE_FLEET_ROUTE_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "fleet_route",
    "run_id": "r18c2d3e4-1a2b-0", "decision": "hedge",
    "replica": 2, "winner": 2, "op": "predict", "tenant": "acme",
    "rows": 3, "attempt": 1, "latency_ms": 18.4, "ewma_ms": 3.1,
    "median_ms": 2.9, "outstanding": 1, "verdict": "ok",
    "generation": 5, "error": None, "source": "serve.router",
    "tool": "serve.router",
}

EXAMPLE_REPLICA_VERDICT_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "replica_verdict",
    "run_id": "r18c2d3e4-1a2b-0", "replica": 1, "verdict": "slow",
    "age_s": 0.8, "phase": "slow", "previous": "ok", "generation": 5,
    "source": "serve.router", "tool": "serve.router",
}

EXAMPLE_SHARD_QUARANTINE_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "shard_quarantine",
    "run_id": "r18c2d3e4-1a2b-0", "shard": "parts/part-00003.txt",
    "shard_index": 3, "reason": "ValueError: malformed LIBSVM line",
    "attempts": 3, "rows_lost": None, "healthy": 7, "total": 8,
    "data_fraction": 0.875, "epoch": 2, "source": "streaming",
    "tool": "stream_drill",
}

EXAMPLE_STREAM_EPOCH_RECORD = {
    "schema_version": SCHEMA_VERSION, "kind": "stream_epoch",
    "run_id": "r18c2d3e4-1a2b-0", "epoch": 5, "batches": 12,
    "rows": 1536, "pass_s": 0.412, "stall_s": 0.031,
    "stall_fraction": 0.0752, "resumed_from_batch": 7,
    "skipped_batches": 7, "quarantined": 1, "prefetch": 2,
    "contention_flagged": False, "source": "streaming",
    "tool": "stream_drill",
}

# the kind-keyed table selfcheck iterates — graftlint's schema-drift
# rule cross-checks that EVERY registered kind appears here (and has a
# Telemetry helper), so a new kind cannot land without selfcheck
# coverage
EXAMPLES: Dict[str, dict] = {
    "run": EXAMPLE_RUN_RECORD,
    "iteration": EXAMPLE_ITERATION_RECORD,
    "span": EXAMPLE_SPAN_RECORD,
    "metrics": EXAMPLE_METRICS_RECORD,
    "program_cost": EXAMPLE_PROGRAM_COST_RECORD,
    "numerics_failure": EXAMPLE_NUMERICS_FAILURE_RECORD,
    "attempt": EXAMPLE_ATTEMPT_RECORD,
    "recovery": EXAMPLE_RECOVERY_RECORD,
    "heartbeat": EXAMPLE_HEARTBEAT_RECORD,
    "chaos": EXAMPLE_CHAOS_RECORD,
    "journal_replay": EXAMPLE_JOURNAL_REPLAY_RECORD,
    "degraded": EXAMPLE_DEGRADED_RECORD,
    "contract_pin": EXAMPLE_CONTRACT_PIN_RECORD,
    "serve_request": EXAMPLE_SERVE_REQUEST_RECORD,
    "serve_latency": EXAMPLE_SERVE_LATENCY_RECORD,
    "trace_summary": EXAMPLE_TRACE_SUMMARY_RECORD,
    "scaling_curve": EXAMPLE_SCALING_CURVE_RECORD,
    "skew_estimate": EXAMPLE_SKEW_ESTIMATE_RECORD,
    "rebalance": EXAMPLE_REBALANCE_RECORD,
    "canary": EXAMPLE_CANARY_RECORD,
    "promotion": EXAMPLE_PROMOTION_RECORD,
    "fleet_route": EXAMPLE_FLEET_ROUTE_RECORD,
    "replica_verdict": EXAMPLE_REPLICA_VERDICT_RECORD,
    "shard_quarantine": EXAMPLE_SHARD_QUARANTINE_RECORD,
    "stream_epoch": EXAMPLE_STREAM_EPOCH_RECORD,
}


def selfcheck() -> Tuple[bool, List[str]]:
    """Validate every example record (one per registered kind), a JSON
    round-trip, and an automatic negative sweep (every required field
    of every kind, when deleted, MUST fail validation).  Returns
    ``(ok, messages)`` — the ``python -m spark_agd_tpu.obs --selfcheck``
    body."""
    msgs: List[str] = []
    ok = True
    missing = [k for k in KINDS if k not in EXAMPLES]
    if missing:
        ok = False
        msgs.append(f"FAIL: kinds without an example record: {missing}")
    for name, rec in EXAMPLES.items():
        errs = validate_record(json.loads(json.dumps(rec)))
        if errs:
            ok = False
            msgs.append(f"FAIL example {name} record: {errs}")
        else:
            msgs.append(f"ok: example {name} record validates "
                        f"(round-tripped through JSON)")
    # negative sweep: deleting ANY required field must be rejected
    for name, rec in EXAMPLES.items():
        for field in _REQUIRED[name]:
            bad = dict(rec)
            del bad[field]
            if validate_record(bad):
                msgs.append(f"ok: negative control ({name} missing "
                            f"{field}) rejected")
            else:
                ok = False
                msgs.append(f"FAIL: {name} record missing {field} "
                            "passed validation")
    stamped = stamp({"value": 1.0}, tool="selfcheck")
    errs = validate_record(stamped)
    if errs:
        ok = False
        msgs.append(f"FAIL: stamp() output invalid: {errs}")
    else:
        msgs.append("ok: stamp() emits a valid run record")
    msgs.append("selfcheck " + ("PASSED" if ok else "FAILED"))
    return ok, msgs
