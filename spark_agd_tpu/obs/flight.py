"""The crash flight recorder: the last seconds of telemetry, always on.

A post-mortem's first question is "what was this host doing just before
it died?" — and the JSONL stream's answer is whatever the stdio buffer
happened to flush, while the journal (``resilience.journal``) records
only DECISIONS by design.  The flight recorder fills the gap the way
an aircraft FDR does: a bounded in-memory ring of the last N telemetry
records of EVERY kind (cheap: one deque append per record, no I/O),
flushed to a CRC-framed dump file only when something goes wrong.

- :class:`FlightRecorder` is an ``obs.sinks.Sink`` — ``Telemetry``
  attaches one by default (``flight=False`` opts out), so the ring is
  populated on every telemetered run with zero configuration;
- :meth:`FlightRecorder.dump` writes the ring as ``AGDFDR01`` followed
  by the exact per-record frames of ``resilience.journal`` (``<II``
  length+CRC32 over canonical JSON) — so a dump torn by the very crash
  it documents replays bit-identically up to the torn tail, with the
  same stop conditions the journal already proves;
- :func:`dump_on_failure` is the one-point wiring for failure paths:
  the supervisor (``SupervisorGivingUp``), the degrade layer
  (``QuorumLost``), and the serving queue (``ServeOverloaded``) call it
  with a reason; it finds the run's recorder, dumps (rate-limited per
  reason — an overload storm must not write a dump per rejected
  request), and puts the dump itself on record as a ``recovery``
  record with ``action="flight_dump"``.

Dumps only happen when a destination is known: ``Telemetry(flight_dir=
...)`` (the drills set it) or an explicit ``path``.  Without one,
``dump_on_failure`` is a no-op — the ring still exists for programmatic
inspection (:meth:`FlightRecorder.snapshot`), but no file appears
behind the operator's back.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .sinks import Sink

MAGIC = b"AGDFDR01"
DEFAULT_CAPACITY = 512

# a second dump for the same reason within this window is suppressed
# (an overload storm calls dump_on_failure per rejection)
DEFAULT_MIN_INTERVAL_S = 5.0


def _journal():
    """The framing provider (``resilience.journal``), imported lazily:
    ``obs`` must stay importable without dragging the resilience
    package in at module load."""
    from ..resilience import journal

    return journal


class FlightRecorder(Sink):
    """See module docstring.  ``capacity`` bounds host memory (records
    are plain dicts — hundreds of bytes each); ``directory`` is where
    :meth:`dump` lands when no explicit path is given."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, *,
                 directory: Optional[str] = None,
                 min_dump_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 clock=time.monotonic):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.directory = directory
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._clock = clock
        self._ring: deque = deque(maxlen=self.capacity)
        # emit() appends from every telemetered thread while dump()
        # iterates — an unguarded deque raises "mutated during
        # iteration" exactly when a failure path dumps under live load
        self._ring_lock = threading.Lock()
        self._seen = 0
        self._last_dump_t: Dict[str, float] = {}
        self._dump_counter = 0
        self.dumps: List[str] = []     # every path written, in order
        self.written: List[bytes] = []  # the LAST dump's payload bytes
        #                                 (bit-identity assertions)

    # -- the sink half ----------------------------------------------------
    def emit(self, record: dict) -> None:
        with self._ring_lock:
            self._seen += 1
            self._ring.append(dict(record))

    @property
    def seen(self) -> int:
        """Records observed over the recorder's lifetime (>= ring
        length once the ring has wrapped)."""
        return self._seen

    def snapshot(self) -> List[dict]:
        """The ring's current contents, oldest first."""
        with self._ring_lock:
            return [dict(r) for r in self._ring]

    # -- the dump half ----------------------------------------------------
    def dump(self, path: Optional[str] = None, *,
             reason: Optional[str] = None,
             force: bool = False) -> Optional[str]:
        """Write the ring to ``path`` (or a fresh file in
        ``directory``) and return the path — or None when there is no
        destination, the ring is empty, or the per-reason rate limit
        suppressed a repeat.  The write is tempfile+rename atomic: a
        half-written dump never shadows an older complete one."""
        if not self._ring:
            return None
        key = reason or "manual"
        now = self._clock()
        last = self._last_dump_t.get(key)
        if not force and last is not None \
                and now - last < self.min_dump_interval_s:
            return None
        if path is None:
            if self.directory is None:
                return None
            os.makedirs(self.directory, exist_ok=True)
            self._dump_counter += 1
            path = os.path.join(
                self.directory,
                f"flight-{key}-{os.getpid()}-{self._dump_counter}.bin")
        else:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
        journal = _journal()
        with self._ring_lock:
            ring = list(self._ring)
        frames = [journal.encode_record(rec) for rec in ring]
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            for frame in frames:
                f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._last_dump_t[key] = now
        self.dumps.append(path)
        # the payload bytes (frame minus the 8-byte header) this dump
        # committed — what a replay must reproduce bit-identically
        self.written = [fr[journal.FRAME_SIZE:] for fr in frames]
        return path


def load_dump(path: str):
    """Replay one flight dump — the journal's torn-tail-tolerant walk
    under the flight magic.  Returns a
    ``resilience.journal.JournalReplay``: everything before the first
    torn frame / short payload / CRC mismatch, plus how many bytes of
    tail were unrecoverable and why."""
    return _journal().replay(path, magic=MAGIC)


def find_recorder(telemetry) -> Optional[FlightRecorder]:
    """The recorder attached to ``telemetry``'s bus (None when the run
    opted out)."""
    if telemetry is None:
        return None
    for sink in getattr(telemetry, "bus").sinks:
        if isinstance(sink, FlightRecorder):
            return sink
    return None


def dump_on_failure(telemetry, reason: str, *,
                    path: Optional[str] = None) -> Optional[str]:
    """The failure-path hook: dump ``telemetry``'s flight ring tagged
    with ``reason`` and put the dump on record.  Silently a no-op when
    there is no telemetry, no recorder, no destination, or the
    per-reason rate limit held — a failure path must never fail again
    inside its own post-mortem hook."""
    recorder = find_recorder(telemetry)
    if recorder is None:
        return None
    try:
        out = recorder.dump(path, reason=reason)
    except OSError:
        # a dying filesystem must not mask the real failure
        return None
    if out is not None:
        telemetry.recovery(action="flight_dump", path=out,
                           reason=str(reason), source="flight")
    return out
