"""Event bus: fan records out to sinks, multihost-aware.

One ``EventBus`` owns an ordered list of sinks (``obs.sinks``) and a
host-gating mode.  On a multi-host SPMD job every process executes the
same program — including its ``jax.debug.callback`` host callbacks — so
an ungated bus would write N copies of every record.  Modes:

- ``"all"`` (default): every host emits.  On a single host this is the
  no-op gate; on multihost pair it with per-host-suffixed sink paths
  (``parallel.multihost.host_suffixed``) so hosts never write the same
  file.
- ``"primary"``: only process 0 emits (rank-0-only logging, the common
  production choice for replicated scalars).

The gate resolves lazily on first emit (``jax.process_index`` touches
the backend, which telemetry construction must not force) and is a
no-op on a single host by construction.  Sink failures are counted and
logged once, never raised — telemetry must not kill the run it
observes.
"""

from __future__ import annotations

import logging
import threading
from typing import Iterable, List

from .sinks import Sink

logger = logging.getLogger("spark_agd_tpu")


class EventBus:
    def __init__(self, sinks: Iterable[Sink] = (),
                 host_mode: str = "all"):
        if host_mode not in ("all", "primary"):
            raise ValueError(
                f"host_mode must be 'all' or 'primary', got {host_mode!r}")
        self.sinks: List[Sink] = list(sinks)
        self.host_mode = host_mode
        self._emit_here = None  # lazily resolved host gate
        self.sink_errors = 0
        self._warned = False
        # the serving plane emits from many threads at once (client
        # spans, router decisions, queue latencies); a raw file write
        # interleaves under that load, so one bus-level lock keeps
        # every sink's record boundaries intact
        self._lock = threading.Lock()

    def _host_ok(self) -> bool:
        if self.host_mode == "all":
            return True
        if self._emit_here is None:
            try:
                from ..parallel import multihost

                self._emit_here = multihost.is_primary_host()
            except Exception:  # noqa: BLE001 — no backend yet / no jax:
                # gating open is the single-host-correct default
                self._emit_here = True
        return self._emit_here

    def emit(self, record: dict) -> None:
        if not self._host_ok():
            return
        with self._lock:
            for sink in self.sinks:
                try:
                    sink.emit(record)
                except Exception as e:  # noqa: BLE001 — observability
                    # must never kill the observed run
                    self.sink_errors += 1
                    if not self._warned:
                        self._warned = True
                        logger.warning(
                            "telemetry sink %s failed (%s: %s); "
                            "further sink errors are counted "
                            "silently (bus.sink_errors)",
                            type(sink).__name__, type(e).__name__, e)

    def flush(self) -> None:
        for sink in self.sinks:
            try:
                sink.flush()
            except Exception:  # noqa: BLE001
                self.sink_errors += 1

    def close(self) -> None:
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:  # noqa: BLE001
                self.sink_errors += 1
