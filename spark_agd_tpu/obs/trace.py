"""Hierarchical trace contexts: causal parentage for every span.

The telemetry stream before this module was FLAT: span timers, attempt
ledgers, and serve latency records all landed in one JSONL with no way
to say *this* retry belongs to *that* supervised run, or *this* engine
call served *that* request.  The per-host timeline diagnosis that
drives distributed-ML tuning (PAPERS.md arXiv 1612.01437: stragglers
and partition skew dominate cost) needs the causal tree.  This module
is the context layer:

- a :class:`SpanContext` is ``(trace_id, span_id, parent_id, process)``
  — one node of one trace's tree, with the emitting host's rank
  stamped;
- **in-thread propagation** is implicit through a ``contextvars``
  context variable: ``Telemetry.trace_span`` opens a span under the
  current context and installs itself as the new current;
- **cross-thread and cross-process propagation is EXPLICIT**: threads
  do not inherit the context variable (each ``threading.Thread`` starts
  with its own context), so a handoff captures
  :func:`current_context` on the submitting side and the worker adopts
  it with :func:`activate` (the serve ``MicroBatchQueue`` does exactly
  this), and a child process receives the wire form
  (:meth:`SpanContext.to_wire`) via the :data:`TRACE_ENV` environment
  variable (``tools/dist_fault_drill.py`` joins two gloo processes into
  one tree this way);
- spans ride the existing ``span`` record kind with OPTIONAL trace
  fields (``trace_id``/``span_id``/``parent_id``/``process``/
  ``t_start_unix``/``status``), so untraced spans and every existing
  consumer keep working unchanged.  Each traced span emits an ``open``
  record when it starts (flushed immediately — a SIGKILLed host leaves
  its open spans on disk, which is how a kill shows up as a TRUNCATED
  span in ``obs.timeline``) and a closing record with the measured
  duration.

Zero overhead when unused: nothing here touches jax tracing or the
compiled program — a fit run with tracing enabled lowers to the
IDENTICAL HLO (pinned by ``tests/test_trace.py``), because spans are
pure host-side bookkeeping around the program, never inside it.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import os
from typing import Optional

# the environment variable a parent process hands its context to a
# child through (the drills' cross-process propagation channel)
TRACE_ENV = "AGD_TRACE_CONTEXT"

_current: contextvars.ContextVar = contextvars.ContextVar(
    "agd_trace_context", default=None)


def new_trace_id() -> str:
    """Process-unique random trace id (``t`` + 16 hex chars)."""
    return "t" + os.urandom(8).hex()


def new_span_id() -> str:
    """Random span id (``s`` + 12 hex chars)."""
    return "s" + os.urandom(6).hex()


def process_index() -> int:
    """This process's SPMD rank — WITHOUT forcing backend
    initialization: before ``jax.distributed.initialize`` (or in a
    jax-free consumer) the rank is 0 by definition, and touching
    ``jax.process_index`` here would instantiate a backend behind the
    caller's platform configuration."""
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return 0
        import jax

        return jax.process_index()
    except Exception:  # noqa: BLE001 — no jax / private API moved
        return 0


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """One node of a trace tree — immutable, cheap to hand around."""

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    process: int = 0

    def child(self, process: Optional[int] = None) -> "SpanContext":
        """A fresh span under this one (same trace, new span id)."""
        return SpanContext(
            trace_id=self.trace_id, span_id=new_span_id(),
            parent_id=self.span_id,
            process=self.process if process is None else int(process))

    # -- wire form (cross-process propagation) ---------------------------
    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "process": self.process}

    @classmethod
    def from_wire(cls, d: dict) -> "SpanContext":
        return cls(trace_id=str(d["trace_id"]),
                   span_id=str(d["span_id"]),
                   parent_id=(None if d.get("parent_id") is None
                              else str(d["parent_id"])),
                   process=int(d.get("process", 0)))

    def to_env_value(self) -> str:
        """The :data:`TRACE_ENV` payload (canonical JSON)."""
        return json.dumps(self.to_wire(), sort_keys=True)


def new_root(process: Optional[int] = None) -> SpanContext:
    """A fresh trace's root context."""
    return SpanContext(trace_id=new_trace_id(), span_id=new_span_id(),
                       parent_id=None,
                       process=process_index() if process is None
                       else int(process))


def child_of(ctx: Optional[SpanContext],
             process: Optional[int] = None) -> SpanContext:
    """A span context under ``ctx`` — or a fresh root when ``ctx`` is
    None (an orphan request with no caller trace starts its own)."""
    if ctx is None:
        return new_root(process)
    return ctx.child(process=process_index() if process is None
                     else int(process))


def current_context() -> Optional[SpanContext]:
    """The context the running thread is inside (None outside any
    traced span) — capture this at a thread/queue handoff boundary."""
    return _current.get()


@contextlib.contextmanager
def activate(ctx: Optional[SpanContext]):
    """Adopt ``ctx`` as the current context for the ``with`` body — the
    EXPLICIT propagation primitive for thread handoffs and for child
    processes that parsed :func:`from_env`.  ``activate(None)`` is a
    no-op, so call sites never branch."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def from_env(environ=None) -> Optional[SpanContext]:
    """The context a parent process published through
    :data:`TRACE_ENV`; None when absent or unparseable (a garbled env
    var must not kill the child it was meant to observe)."""
    raw = (os.environ if environ is None else environ).get(TRACE_ENV)
    if not raw:
        return None
    try:
        return SpanContext.from_wire(json.loads(raw))
    except (ValueError, KeyError, TypeError):
        return None


class TracedSpan:
    """The context manager behind ``Telemetry.trace_span`` — opens a
    span under the current (or explicit ``parent``) context, installs
    itself as current for the body, and emits the open/close record
    pair.  ``__enter__`` returns the span's :class:`SpanContext`;
    :meth:`note` adds fields to the closing record (the supervisor
    stamps attempt outcomes this way)."""

    def __init__(self, telemetry, name: str,
                 parent: Optional[SpanContext] = None, fields=None):
        self._tel = telemetry
        self.name = str(name)
        self._parent = parent
        self._fields = dict(fields or {})
        self.ctx: Optional[SpanContext] = None
        self._token = None
        self._t0 = None
        self._t_start_unix = None

    def note(self, **fields) -> "TracedSpan":
        """Merge ``fields`` into the closing span record."""
        self._fields.update(fields)
        return self

    def _record(self, seconds: float, status: str) -> dict:
        from . import schema

        rec = schema.span_record(self._tel.run_id, self.name,
                                 float(seconds))
        rec.update(trace_id=self.ctx.trace_id, span_id=self.ctx.span_id,
                   parent_id=self.ctx.parent_id,
                   process=int(self.ctx.process), status=status,
                   t_start_unix=round(self._t_start_unix, 6))
        rec.update(self._fields)
        return rec

    def __enter__(self) -> SpanContext:
        import time

        parent = (self._parent if self._parent is not None
                  else current_context())
        self.ctx = child_of(parent)
        self._token = _current.set(self.ctx)
        self._t_start_unix = time.time()
        self._t0 = time.perf_counter()
        # the open record is flushed immediately: if this process dies
        # (SIGKILL, OOM) before closing, the span survives on disk as
        # the TRUNCATED evidence of where death struck
        self._tel.emit(self._record(0.0, "open"))
        self._tel.flush()
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        import time

        seconds = time.perf_counter() - self._t0
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        if exc_type is not None:
            self._fields.setdefault(
                "error", f"{exc_type.__name__}: {exc}")
            status = "error"
        else:
            status = self._fields.pop("status", "ok")
        self._tel.registry.counter("trace.spans").inc()
        self._tel.emit(self._record(seconds, status))
        return False
