"""``spark_agd_tpu.obs`` — the unified telemetry subsystem.

Three layers (see ``docs/OBSERVABILITY.md`` for the guide):

1. **Metrics registry** (``obs.registry``): counters, gauges, span
   timers — cheap in-process instruments, snapshotted on demand.
2. **Event bus + sinks** (``obs.events`` / ``obs.sinks``): records
   stream to in-memory, JSONL, CSV, stdlib-logging, or (optional)
   TensorBoard sinks; multihost-aware (rank-0-only or per-host files).
3. **Canonical run-record schema** (``obs.schema``): the ONE JSONL
   record family every producer stamps (``benchmarks/run.py``,
   ``bench.py``, ``utils/logging.py``) and ``tools/agd_report.py``
   consumes.  ``python -m spark_agd_tpu.obs --selfcheck`` validates it.
4. **Compiled-program introspection + perf gate** (``obs.introspect`` /
   ``obs.perfgate``): ``ProgramCost`` census of any runner's compiled
   program (FLOPs, HBM footprint, per-collective counts) emitted as
   ``program_cost`` records, and the regression gate
   (``tools/perf_gate.py``) that compares candidate run-record JSONLs
   against a baseline on wall clock AND compiled-program facts.

The headline consumer is **live in-loop streaming**: pass
``telemetry=Telemetry(...)`` to ``api.run`` / ``api.make_runner`` (or
the L-BFGS runners) and the fused ``lax.while_loop`` emits one record
per iteration *while the compiled program runs*, via
``jax.debug.callback``.  Off by default — the callback costs a host
round-trip per iteration, so the untelemetered program is bit-identical
to before (no callback in the HLO) and timings are unaffected.
"""

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    MetricsRegistry,
    SpanTimer,
    default_registry,
)
from .events import EventBus  # noqa: F401
from .sinks import (  # noqa: F401
    CSVSink,
    InMemorySink,
    JSONLSink,
    LoggingSink,
    Sink,
    TensorBoardSink,
)
from .telemetry import Telemetry  # noqa: F401
from . import (  # noqa: F401
    flight,
    introspect,
    perfgate,
    scaling,
    schema,
    timeline,
    trace,
)
from .flight import FlightRecorder, dump_on_failure, load_dump  # noqa: F401
from .trace import SpanContext, TracedSpan  # noqa: F401
from .introspect import (  # noqa: F401
    ProgramCost,
    analyze,
    analyze_compiled,
    analyze_runner,
    collective_census,
    count_ops,
    environment_fingerprint,
)
from .scaling import (  # noqa: F401
    ContentionPolicy,
    ContentionSentinel,
    CurvePolicy,
    CurveVerdict,
    SpinProbe,
    check_curve,
    environment_key,
    fit_serial_fraction,
    host_fingerprint,
    weak_scaling_efficiency,
)
from .schema import (  # noqa: F401
    SCHEMA_VERSION,
    attempt_record,
    iteration_record,
    new_run_id,
    numerics_failure_record,
    program_cost_record,
    read_jsonl,
    recovery_record,
    run_record,
    span_record,
    stamp,
    validate_record,
)
