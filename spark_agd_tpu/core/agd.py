"""Fused TPU-native AGD: the whole optimizer is ONE compiled XLA program.

The reference runs a driver-orchestrated loop: per outer iteration it ships
weights to executors by broadcast, tree-reduces (loss, grad) back, and does
the Auslender–Teboulle / backtracking / restart math on the driver in Breeze
(reference ``AcceleratedGradientDescent.scala:237-332``; cost shape SURVEY
§3.1: 2-3 network round-trips per iteration).  Here the inversion promised by
SURVEY §7: weights, data, and every recurrence live on device; the outer
``for``/inner ``while(true)`` become nested ``lax.while_loop``s; the
distributed reduce is whatever collective the mesh layer compiled into
``smooth``; the host launches one program and reads back scalars at the end.

Parity quirks carried over exactly (each tested against the NumPy oracle in
``tests/test_agd_core.py``):

- ``theta = +inf`` first-iteration identity (reference ``:226, :248``) —
  IEEE ``x/inf == 0`` makes the first trial evaluate at ``w0``.
- backtracking estimator switch ``backtrack_simple`` at tol 1e-10
  (``:272-279``), and the infinite-localL L-update dance (``:285-292``).
- loss history at x = ``f(x) + reg(x)`` (``:302-307``).  The reference pays
  a third full distributed pass (loss AND gradient) for this; the gradient
  of that pass is *discarded* (only the ``step=0`` prox trick uses it, which
  ignores g).  We instead reuse the ``f(x)`` the backtracking loop already
  computed — same argument, same kernel, agreeing to ~1 ulp (XLA may fuse
  the two call sites differently) — and call ``reg_value`` directly.  One
  fewer
  full pass per iteration than the reference at identical numerics
  (``loss_mode='x'``); ``'x_strict'`` recomputes like the reference for
  cost-parity benchmarking; ``'y'`` is the cheaper variant the reference
  left commented out (``:296-300``).
- NaN/Inf loss guard (``:309-312``); convergence rules incl. the
  ``nIter > 1`` gate on exact-zero steps (``:314-324``); O'Donoghue-Candes
  gradient-test restart (``:326-331``).

One deliberate deviation: the reference's inner ``while(true)`` spins forever
if the loss goes NaN mid-backtracking (NaN comparisons are all false).  Here
a non-finite ``f_y`` accepts the trial immediately so the outer NaN guard
aborts the run, and ``max_backtracks`` (default 100, never hit on finite
data) bounds the inner loop — both strictly safer, neither reachable on the
oracle-parity test surface.

Weights may be any pytree (``core.tvec``); scalars inherit the loss dtype.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import tvec

SmoothFn = Callable[[Any], Tuple[jax.Array, Any]]
ProxFn = Callable[[Any, Any, jax.Array], Tuple[Any, jax.Array]]
RegValFn = Callable[[Any], jax.Array]
LossFn = Callable[[Any], jax.Array]


@dataclass(frozen=True)
class AGDConfig:
    """The nine reference knobs (defaults from reference ``:44-51``) plus
    the in-body constant ``backtrack_tol`` (``:235``) and fused-loop extras."""

    convergence_tol: float = 1e-4
    num_iterations: int = 100
    l0: float = 1.0
    l_exact: float = math.inf
    beta: float = 0.5
    alpha: float = 0.9
    may_restart: bool = True
    backtrack_tol: float = 1e-10
    max_backtracks: int = 100
    loss_mode: str = "x"  # 'x' | 'x_strict' | 'y'


class AGDWarmState(NamedTuple):
    """The complete inter-iteration carry of the optimizer — what SURVEY §5
    calls "2 vectors + 3 scalars" (plus the estimator-switch flag): enough
    to continue a run exactly where it stopped.  ``prior_iters`` feeds the
    ``nIter > 1`` gate on exact-zero steps (reference ``:317-321``) so a
    resumed run makes the same stop decisions as an uninterrupted one."""

    x: Any
    z: Any
    theta: Any
    big_l: Any
    bts: Any
    prior_iters: Any

    @classmethod
    def initial(cls, w0: Any, config: "AGDConfig") -> "AGDWarmState":
        """The iteration-zero carry (reference init ``:224-235``): the ONE
        definition all three drivers (fused, host, checkpointed) expand, so
        cold start and resume-from-zero cannot drift apart."""
        return cls(x=w0, z=w0, theta=math.inf, big_l=float(config.l0),
                   bts=True, prior_iters=0)


class AGDResult(NamedTuple):
    weights: Any
    loss_history: jax.Array  # (num_iterations,), NaN-padded past num_iters
    num_iters: jax.Array  # iterations actually executed
    aborted_non_finite: jax.Array
    final_l: jax.Array  # Lipschitz estimate at exit
    num_backtracks: jax.Array
    num_restarts: jax.Array
    # the carry needed to continue this run (checkpoint/resume; utils/)
    final_z: Any
    final_theta: jax.Array
    final_bts: jax.Array
    converged: jax.Array  # stopped by its own criteria (not cap, not abort)
    # per-iteration diagnostics (NaN/0-padded): the values the reference
    # computes and discards (SURVEY §5 metrics gap)
    diag_l: jax.Array
    diag_theta: jax.Array
    diag_step: jax.Array
    diag_restarted: jax.Array


class _Outer(NamedTuple):
    x: Any
    z: Any
    theta: jax.Array
    big_l: jax.Array
    bts: jax.Array  # backtrack_simple
    it: jax.Array
    done: jax.Array
    aborted: jax.Array
    loss_hist: jax.Array
    n_bt: jax.Array
    n_restart: jax.Array
    diag_l: jax.Array
    diag_theta: jax.Array
    diag_step: jax.Array
    diag_restarted: jax.Array


class _Trial(NamedTuple):
    theta: jax.Array
    big_l: jax.Array
    x: Any
    y: Any
    z: Any
    f_y: jax.Array
    g_y: Any
    f_x: jax.Array  # f at the trial x (reused for loss history)
    bts: jax.Array
    accept: jax.Array
    n_bt: jax.Array


def _replica_gated(cb: Callable) -> Callable:
    """Host-side wrapper for the telemetry callback under the sharded
    carry: every replica's program invokes the callback (the emission
    lives inside a ``shard_map`` body), but only replica 0's invocation
    reaches the stream — N identical records per iteration would
    corrupt every downstream consumer that counts them."""

    def gated(replica, **kw):
        if int(replica) == 0:
            cb(**kw)

    return gated


def run_agd(
    smooth: SmoothFn,
    prox: ProxFn,
    reg_value: RegValFn,
    w0: Any,
    config: AGDConfig,
    *,
    smooth_loss: LossFn | None = None,
    warm: AGDWarmState | None = None,
    telemetry_cb: Callable | None = None,
    axis_name: str | None = None,
) -> AGDResult:
    """Pure, trace-compatible AGD.  Wrap in ``jax.jit`` (the API layer does).

    ``smooth(w) -> (mean_loss, mean_grad)`` — built by the mesh layer, its
    internals carry the cross-device reduction.  ``prox(w, g, step) ->
    (w_new, reg_value)``; ``reg_value(w)`` reads the penalty without the
    reference's ``step = 0`` prox trick (reference ``:305``).
    ``smooth_loss(w) -> mean_loss`` is an optional loss-only evaluation used
    by ``loss_mode='x'`` when backtracking is disabled (``beta >= 1``).

    ``warm`` resumes from a saved ``AGDWarmState`` (``w0`` is then ignored
    except as the structure template): the run continues bit-exactly where
    the checkpointed one stopped, executing up to ``config.num_iterations``
    *further* iterations.

    ``telemetry_cb`` (opt-in live streaming, ``obs.Telemetry.
    iteration_callback``): a host function invoked via
    ``jax.debug.callback`` from inside the compiled loop, once per
    executed iteration with the per-iteration scalars (it, loss, big_l,
    theta, step, restarted) — the values ``diag_*`` only surface after
    the program returns.  COSTS a host round-trip per iteration (an
    outfeed on TPU), which is exactly the traffic the fused design
    removed; ``None`` (default) traces the identical program as before
    (no callback in the HLO).

    ``axis_name`` (the sharded weight update, arXiv 2004.13336): when
    set, the caller is running this function inside a ``shard_map`` body
    over that axis with ``w0``/``warm`` holding each replica's 1/N weight
    shard and ``smooth`` returning the matching 1/N *gradient* shard
    (reduce-scatter inside — see ``parallel.sharded_update``).  All the
    elementwise carry math (``tvec.axpby``, prox, the ``z`` restart
    merge) is shard-local and runs unchanged on 1/N of the elements; the
    handful of control scalars that need the *global* vectors — ``xy_sq``,
    the two curvature dots, the convergence norms, the restart dot — are
    assembled from shard-local partial sums via scalar ``lax.psum``,
    so every replica sees identical control flow through both nested
    ``while_loop``s.  ``reg_value`` must likewise return the global
    penalty (callers psum their shard-local value).  ``None`` (default)
    binds the plain ``tvec`` reductions — bit-identical trace to before
    the parameter existed.
    """
    cfg = config
    if cfg.loss_mode not in ("x", "x_strict", "y"):
        raise ValueError(f"unknown loss_mode {cfg.loss_mode!r}")

    dt = jnp.promote_types(
        jnp.result_type(*jax.tree_util.tree_leaves(w0)), jnp.float32)

    def s(v):
        return jnp.asarray(v, dt)

    tol = s(cfg.convergence_tol)
    l_exact = s(cfg.l_exact)
    beta = s(cfg.beta)
    btol = s(cfg.backtrack_tol)
    backtracking = cfg.beta < 1.0  # static: trial-acceptance structure

    if axis_name is None:
        # bit-identical trace to the pre-sharding program: direct aliases,
        # no wrapper frames, nothing new in the jaxpr
        _dot, _sq_norm, _norm = tvec.dot, tvec.sq_norm, tvec.norm
    else:
        # shard-local partial sums -> one scalar psum each: the only
        # cross-replica traffic the carry math itself generates
        def _dot(a, b):
            return lax.psum(tvec.dot(a, b), axis_name)

        def _sq_norm(a):
            return lax.psum(tvec.sq_norm(a), axis_name)

        def _norm(a):
            return jnp.sqrt(_sq_norm(a))

    def trial_cond(c: _Trial) -> jax.Array:
        return jnp.logical_and(~c.accept, c.n_bt < cfg.max_backtracks)

    def norm_smooth(w_like, out):
        """Pin smooth outputs to the carry dtype: a smooth that computes
        in a wider/narrower dtype (e.g. f64 data under x64 with f32
        weights) must not leak its dtype into the while_loop carry —
        that's a trace-time cond/carry mismatch."""
        f, g = out
        return s(f), tvec.tmap(lambda gi, wi: gi.astype(wi.dtype),
                               g, w_like)

    def make_trial_body(x_old, z_old, l_old, theta_old):
        def trial_body(c: _Trial) -> _Trial:
            theta = 2.0 / (1.0 + jnp.sqrt(
                1.0 + 4.0 * (c.big_l / l_old) / (theta_old * theta_old)))
            y = tvec.axpby(1.0 - theta, x_old, theta, z_old)
            f_y, g_y = norm_smooth(x_old, smooth(y))
            step = 1.0 / (theta * c.big_l)
            z = prox(z_old, g_y, step)[0]
            x = tvec.axpby(1.0 - theta, x_old, theta, z)

            if not backtracking:
                return _Trial(theta, c.big_l, x, y, z, f_y, g_y,
                              s(jnp.nan), c.bts, jnp.asarray(True), c.n_bt)

            xy = tvec.sub(x, y)
            xy_sq = _sq_norm(xy)
            # Trivial accepts: exact-zero step (reference :263-267) or a
            # non-finite f_y (deviation: defer to the outer NaN guard
            # instead of spinning — see module docstring).
            trivial = jnp.logical_or(xy_sq == 0.0, ~jnp.isfinite(f_y))

            def accept_trivial(_):
                # x == y exactly when xy_sq == 0, so f_x := f_y is exact.
                return (f_y, jnp.asarray(True), c.big_l, c.bts)

            def eval_fx(_):
                f_x, g_x = norm_smooth(x_old, smooth(x))
                q_x = f_y + _dot(xy, g_y) + 0.5 * c.big_l * xy_sq
                local_simple = (
                    c.big_l + 2.0 * jnp.maximum(f_x - q_x, 0.0) / xy_sq)
                local_curv = 2.0 * _dot(xy, tvec.sub(g_x, g_y)) / xy_sq
                local_l = jnp.where(c.bts, local_simple, local_curv)
                bts_new = jnp.logical_and(
                    c.bts,
                    jnp.abs(f_y - f_x)
                    >= btol * jnp.maximum(jnp.abs(f_x), jnp.abs(f_y)))
                accept = jnp.logical_or(local_l <= c.big_l,
                                        c.big_l >= l_exact)
                # The L-update dance, reference :285-292: for finite localL
                # first clamp L to min(Lexact, localL), then grow by 1/beta;
                # infinite localL degrades to L/beta.
                is_inf = jnp.isinf(local_l)
                l1 = jnp.where(is_inf, c.big_l,
                               jnp.minimum(l_exact, local_l))
                local2 = jnp.where(is_inf, c.big_l, local_l)
                l_next = jnp.minimum(l_exact,
                                     jnp.maximum(local2, l1 / beta))
                return (f_x, accept, jnp.where(accept, c.big_l, l_next),
                        bts_new)

            f_x, accept, big_l, bts = lax.cond(
                trivial, accept_trivial, eval_fx, operand=None)
            return _Trial(theta, big_l, x, y, z, f_y, g_y, f_x, bts, accept,
                          c.n_bt + jnp.where(accept, 0, 1))

        return trial_body

    def outer_body(o: _Outer) -> _Outer:
        x_old, z_old = o.x, o.z
        l_old = o.big_l
        big_l = o.big_l * s(cfg.alpha)
        theta_old = o.theta

        init = _Trial(
            theta=o.theta, big_l=big_l, x=o.x, y=o.x, z=o.z,
            f_y=s(0.0), g_y=tvec.zeros_like(o.x), f_x=s(jnp.nan),
            bts=o.bts, accept=jnp.asarray(False),
            n_bt=jnp.zeros((), jnp.int32))
        body = make_trial_body(x_old, z_old, l_old, theta_old)
        # Run the first trial unconditionally, then loop while rejected —
        # the reference's do-while.
        t = lax.while_loop(trial_cond, body, body(init))

        # ---- loss history (reference :302-307 / commented :296-300) ----
        if cfg.loss_mode == "y":
            loss = t.f_y + s(reg_value(t.y))
        elif cfg.loss_mode == "x_strict":
            loss = s(smooth(t.x)[0]) + s(reg_value(t.x))
        else:  # 'x': reuse the backtracking pass's f(x)
            if backtracking:
                loss = t.f_x + s(reg_value(t.x))
            else:
                ls = smooth_loss or (lambda w: smooth(w)[0])
                loss = s(ls(t.x)) + s(reg_value(t.x))

        it_new = o.it + 1
        loss_hist = o.loss_hist.at[o.it].set(loss)

        aborted = ~jnp.isfinite(t.f_y)  # NaN guard, reference :309-312
        norm_x = _norm(t.x)
        norm_dx = _norm(tvec.sub(t.x, x_old))
        done_zero = jnp.logical_and(norm_dx == 0.0,
                                    it_new + prior_iters > 1)
        done_tol = norm_dx < tol * jnp.maximum(norm_x, 1.0)
        done = aborted | done_zero | done_tol

        # Restart (reference :326-331), only on the continue path.
        restart = jnp.asarray(False)
        if cfg.may_restart:
            restart = jnp.logical_and(
                _dot(t.g_y, tvec.sub(t.x, x_old)) > 0.0, ~done)
        z_new = tvec.tmap(
            lambda zi, xi: jnp.where(restart, xi, zi), t.z, t.x)
        theta_new = jnp.where(restart, s(jnp.inf), t.theta)
        bts_new = jnp.logical_or(restart, t.bts)

        if telemetry_cb is not None:
            # live stream: the same scalars the diag_* arrays record,
            # emitted to the host WHILE the compiled loop runs
            scalars = dict(it=it_new, loss=loss, big_l=t.big_l,
                           theta=t.theta, step=1.0 / (t.theta * t.big_l),
                           restarted=restart)
            if axis_name is None:
                jax.debug.callback(telemetry_cb, **scalars)
            else:
                jax.debug.callback(
                    _replica_gated(telemetry_cb),
                    replica=lax.axis_index(axis_name), **scalars)

        return _Outer(
            x=t.x, z=z_new, theta=theta_new, big_l=t.big_l, bts=bts_new,
            it=it_new, done=done, aborted=aborted, loss_hist=loss_hist,
            n_bt=o.n_bt + t.n_bt,
            n_restart=o.n_restart + jnp.where(restart, 1, 0),
            diag_l=o.diag_l.at[o.it].set(t.big_l),
            diag_theta=o.diag_theta.at[o.it].set(t.theta),
            diag_step=o.diag_step.at[o.it].set(1.0 / (t.theta * t.big_l)),
            diag_restarted=o.diag_restarted.at[o.it].set(restart),
        )

    def outer_cond(o: _Outer) -> jax.Array:
        return jnp.logical_and(o.it < cfg.num_iterations, ~o.done)

    n = cfg.num_iterations
    if warm is None:
        warm = AGDWarmState.initial(w0, cfg)
    x0, z0 = warm.x, warm.z
    theta0, l_init = s(warm.theta), s(warm.big_l)
    bts0 = jnp.asarray(warm.bts, jnp.bool_)
    prior_iters = jnp.asarray(warm.prior_iters, jnp.int32)
    init = _Outer(
        x=x0, z=z0,
        theta=theta0, big_l=l_init, bts=bts0,
        it=jnp.zeros((), jnp.int32), done=jnp.asarray(False),
        aborted=jnp.asarray(False),
        loss_hist=jnp.full((n,), jnp.nan, dt),
        n_bt=jnp.zeros((), jnp.int32), n_restart=jnp.zeros((), jnp.int32),
        diag_l=jnp.full((n,), jnp.nan, dt),
        diag_theta=jnp.full((n,), jnp.nan, dt),
        diag_step=jnp.full((n,), jnp.nan, dt),
        diag_restarted=jnp.zeros((n,), jnp.bool_),
    )
    o = lax.while_loop(outer_cond, outer_body, init) if n > 0 else init

    return AGDResult(
        weights=o.x, loss_history=o.loss_hist, num_iters=o.it,
        aborted_non_finite=o.aborted, final_l=o.big_l,
        num_backtracks=o.n_bt, num_restarts=o.n_restart,
        final_z=o.z, final_theta=o.theta, final_bts=o.bts,
        converged=jnp.logical_and(o.done, ~o.aborted),
        diag_l=o.diag_l, diag_theta=o.diag_theta, diag_step=o.diag_step,
        diag_restarted=o.diag_restarted,
    )
