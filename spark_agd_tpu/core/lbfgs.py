"""Fused TPU-native L-BFGS: the Optimizer family's quasi-Newton member.

The reference's ``AcceleratedGradientDescent`` implements spark-mllib
1.3.0's ``Optimizer`` trait precisely so it can be swapped with MLlib's
other optimizers inside ``GeneralizedLinearAlgorithm``-style callers —
the reference class doc names the family (reference
``AcceleratedGradientDescent.scala:41-42`` extends ``Optimizer``; SURVEY
§1 L5: "drop-in interchangeable with MLlib's own GradientDescent /
LBFGS").  ``core/gd.py`` provides the GD member; this module provides
the L-BFGS member, so a migrating user finds the whole
``mllib.optimization`` optimizer menu.

MLlib 1.3's LBFGS wraps Breeze's: an m-pair two-loop recursion over a
``CostFun`` whose ``calculate`` is one treeAggregate pass (the same
broadcast + tree-reduce round-trip as AGD's ``applySmooth``), with a
strong-Wolfe line search costing 1-3 more such round-trips per
iteration.  The TPU inversion is the same as ``core/agd.py``: the
whole minimizer — two-loop recursion, Wolfe bracketing/zoom, curvature
updates, convergence test — is ONE ``lax.while_loop`` program; every
objective evaluation is the ``smooth`` callable the caller built, so
the identical core runs single-device or mesh-sharded (the psum lives
inside ``smooth``) and control flow stays coherent across devices
because every decision scalar is post-reduction.

Semantic choices, pinned to the MLlib/Breeze (0.11.x, the spark-1.3 pin)
behavior they mirror:

- ``num_corrections`` (default 10) — MLlib ``LBFGS.setNumCorrections``
  default; history pairs live in fixed ``(m, ...)`` ring buffers so the
  compiled shape is static.
- curvature-pair safeguard: a pair with ``s·y <= 1e-10·‖s‖·‖y‖`` is
  SKIPPED (ring not advanced), the standard positive-definiteness guard.
- line search: strong Wolfe (c1=1e-4, c2=0.9 — Nocedal-Wright alg 3.5/
  3.6 with bisection zoom, the same conditions Breeze's
  ``StrongWolfeLineSearch`` enforces), bounded by ``max_ls_steps``.
- convergence: relative-improvement test
  ``(f_old - f_new) / max(|f_old|, |f_new|, 1) <= tol`` — Breeze's
  ``FirstOrderMinimizer`` improvement check that MLlib's
  ``convergenceTol`` parameterizes; plus an optional gradient-norm stop
  (``grad_tol``, off by default like MLlib).
- a failed line search (no Wolfe point within budget) stops the run
  with ``ls_failed`` set — Breeze throws ``LineSearchFailed``; an
  error flag composes better with vmapped lanes than an abort.
- non-finite objective aborts, like the AGD NaN guard (reference
  ``:309-312``).

The smooth penalty (L2) folds INTO the objective — gradient
``reg·w`` added to the data gradient — exactly how MLlib's LBFGS
``CostFun`` handles ``SquaredL2Updater`` regularization; L1 is not
representable this way (MLlib 1.3 has the same limitation).  The API
layer routes L1 / elastic-net updaters to :func:`run_owlqn` below —
the orthant-wise variant Spark itself adopted after 1.3 — so the
quasi-Newton path covers the full updater menu; the HOST twin
(``core/host_lbfgs.py``) carries both drivers too
(``run_lbfgs_host`` / ``run_owlqn_host``) for streamed and
cross-process objectives.

``loss_history[0]`` is the objective at ``w0``; entry ``i >= 1`` is the
objective after iteration ``i`` (NaN-padded past ``num_iters``), so
``len == iterations executed + 1``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import tvec

ObjectiveFn = Callable[[Any], Tuple[jax.Array, Any]]

# ``ls_stop_reason`` codes (VERDICT r3 weak #3: the single ``ls_failed``
# flag could not distinguish "objective flat at the dtype's noise floor"
# — benign, the tolerance-floor case docs/OPTIMIZERS.md describes — from
# "bracket/zoom logic failed mid-descent" — a bug on a smooth convex
# problem).  Breeze collapses every such outcome into one
# ``LineSearchFailed`` throw (``StrongWolfeLineSearch`` semantics, see
# module docstring); these codes are the finer-grained diagnostic:
LS_STOP_NONE = 0          # line search did not stop the run
LS_STOP_BRACKET = 1       # Wolfe bracket phase exhausted mid-descent
LS_STOP_ZOOM = 2          # Wolfe zoom phase exhausted mid-descent
LS_STOP_NOISE_FLOOR = 3   # no progress beyond the carry dtype's noise
LS_STOP_ARMIJO = 4        # OWL-QN backtracking-Armijo budget exhausted
LS_STOP_REASONS = ("none", "wolfe_bracket_exhausted",
                   "wolfe_zoom_exhausted", "no_progress_at_noise_floor",
                   "armijo_exhausted")


def ls_stop_reason_name(code) -> str:
    """Human-readable name for an ``ls_stop_reason`` code (artifact
    rows carry the name, results carry the traced int)."""
    return LS_STOP_REASONS[int(code)]


@dataclass(frozen=True)
class LBFGSConfig:
    """MLlib ``LBFGS``'s four knobs (their 1.3.0 defaults) plus the
    bounded-loop extras the fused form needs."""

    num_corrections: int = 10
    convergence_tol: float = 1e-4
    num_iterations: int = 100
    grad_tol: float = 0.0  # optional ‖g‖ stop; 0 disables (MLlib has none)
    c1: float = 1e-4
    c2: float = 0.9
    max_ls_steps: int = 12  # per bracket phase and per zoom phase
    max_step_growth: float = 2.0


def check_smooth_penalty(updater, reg_param: float) -> None:
    """Raise for prox-only updaters (the MLlib-1.3 no-OWLQN
    limitation).  Cheap: call BEFORE any data staging so a
    misconfiguration fails free."""
    if updater.smooth_penalty(jnp.zeros((), jnp.float32),
                              float(reg_param)) is None:
        raise ValueError(
            f"{type(updater).__name__} has no smooth penalty: L-BFGS "
            "needs a differentiable objective (MLlib 1.3's LBFGS has "
            "the same limitation — no OWLQN); use "
            "AcceleratedGradientDescent for prox-only penalties")


def make_objective(smooth: Callable, updater, reg_param: float):
    """``objective(w) -> (f, g)``: the smooth data term plus the
    updater's SMOOTH penalty folded in — MLlib LBFGS ``CostFun``'s
    regularization treatment.  Works for the fused loop, the host twin,
    and any smooth builder (in-memory, mesh, streamed).  Raises for
    prox-only updaters (:func:`check_smooth_penalty`)."""
    check_smooth_penalty(updater, reg_param)

    def objective(w):
        f, g = smooth(w)
        pv, pg = updater.smooth_penalty(w, reg_param)
        return f + pv, tvec.add(g, pg)

    return objective


def _carry_dtype(w0):
    return jnp.promote_types(
        jnp.result_type(*jax.tree_util.tree_leaves(w0)), jnp.float32)


def _pin_objective(objective, w_template, sdtype):
    """Pin objective outputs to the carry dtype — the AGD core's
    ``norm_smooth`` convention (core/agd.py): a smooth computing in a
    wider/narrower dtype (f64 data under x64 with f32 weights) must not
    leak its dtype into the ``while_loop`` carry."""
    def obj(w):
        f, g = objective(w)
        return jnp.asarray(f, sdtype), tvec.tmap(
            lambda gi, wi: gi.astype(wi.dtype), g, w_template)
    return obj


class LBFGSResult(NamedTuple):
    weights: Any
    loss_history: jax.Array  # (num_iterations + 1,), NaN-padded
    num_iters: jax.Array
    converged: jax.Array  # stopped by tol (not cap, not failure)
    ls_failed: jax.Array  # line search exhausted its budget
    aborted_non_finite: jax.Array
    grad_norm: jax.Array  # ‖g‖ at exit
    num_fn_evals: jax.Array  # objective evaluations (distributed passes)
    # WHY the line search stopped the run (``LS_STOP_*`` codes;
    # ``LS_STOP_NONE`` when ``ls_failed`` is False)
    ls_stop_reason: Any = LS_STOP_NONE


class _Ring(NamedTuple):
    """Fixed-shape history of the last m curvature pairs."""

    s: Any  # each leaf (m, ...): w_{k+1} - w_k
    y: Any  # each leaf (m, ...): g_{k+1} - g_k
    rho: jax.Array  # (m,): 1 / (s·y)
    count: jax.Array  # pairs stored so far (saturates at m)
    head: jax.Array  # next slot to write


def _ring_init(w0, m, sdtype):
    stack = lambda t: tvec.tmap(
        lambda l: jnp.zeros((m,) + l.shape, l.dtype), t)
    return _Ring(s=stack(w0), y=stack(w0),
                 rho=jnp.zeros((m,), sdtype),
                 count=jnp.zeros((), jnp.int32),
                 head=jnp.zeros((), jnp.int32))


def _tree_index(t, i):
    return tvec.tmap(lambda l: lax.dynamic_index_in_dim(
        l, i, 0, keepdims=False), t)


def _ring_push(ring: _Ring, s, y, accept):
    """Write (s, y) at ``head`` and advance — or leave the ring
    untouched when the curvature safeguard rejects the pair."""
    m = ring.rho.shape[0]
    sy = tvec.dot(s, y)
    put = lambda H, v: tvec.tmap(
        lambda Hl, vl: lax.dynamic_update_index_in_dim(
            Hl, vl.astype(Hl.dtype), ring.head, 0), H, v)
    new = _Ring(
        s=put(ring.s, s), y=put(ring.y, y),
        # guard the rejected-pair branch (s=y=0 after a failed line
        # search -> sy=0): the accept mask discards the slot anyway,
        # but an unconditional 1/0 trips jax debug_infs (r3 advisor)
        rho=ring.rho.at[ring.head].set(
            1.0 / jnp.where(accept, sy, jnp.ones((), sy.dtype))),
        count=jnp.minimum(ring.count + 1, m),
        head=jnp.mod(ring.head + 1, m))
    pick = lambda a, b: jax.tree_util.tree_map(
        lambda x, yv: jnp.where(accept, x, yv), a, b)
    return _Ring(pick(new.s, ring.s), pick(new.y, ring.y),
                 pick(new.rho, ring.rho), pick(new.count, ring.count),
                 pick(new.head, ring.head))


def _two_loop(g, ring: _Ring):
    """H·g via the standard two-loop recursion over the ring, masked to
    the pairs actually stored; H0 = gamma·I scaled by the newest pair."""
    m = ring.rho.shape[0]
    sdtype = ring.rho.dtype

    def newest_first(i):
        return jnp.mod(ring.head - 1 - i, m)

    def oldest_first(i):
        return jnp.mod(ring.head - ring.count + i, m)

    def body1(i, carry):
        q, alphas = carry
        idx = newest_first(i)
        valid = i < ring.count
        a = ring.rho[idx] * tvec.dot(_tree_index(ring.s, idx), q)
        a = jnp.where(valid, a, jnp.zeros((), sdtype))
        q = tvec.axpby(1.0, q, -a, _tree_index(ring.y, idx))
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(
        0, m, body1, (g, jnp.zeros((m,), sdtype)))

    idx_new = jnp.mod(ring.head - 1, m)
    s_n = _tree_index(ring.s, idx_new)
    y_n = _tree_index(ring.y, idx_new)
    yy = tvec.dot(y_n, y_n)
    gamma = jnp.where(
        ring.count > 0,
        tvec.dot(s_n, y_n) / jnp.maximum(yy, jnp.finfo(sdtype).tiny),
        jnp.ones((), sdtype))
    r = tvec.scale(gamma, q)

    def body2(i, r):
        idx = oldest_first(i)
        valid = i < ring.count
        b = ring.rho[idx] * tvec.dot(_tree_index(ring.y, idx), r)
        coef = jnp.where(valid, alphas[idx] - b, jnp.zeros((), sdtype))
        return tvec.axpby(1.0, r, coef, _tree_index(ring.s, idx))

    return lax.fori_loop(0, m, body2, r)


class _LS(NamedTuple):
    t: jax.Array
    f_t: jax.Array
    g_t: Any
    dg_t: jax.Array
    t_lo: jax.Array
    f_lo: jax.Array
    t_hi: jax.Array
    f_hi: jax.Array
    it: jax.Array
    evals: jax.Array
    stage: jax.Array  # 0 bracket, 1 zoom, 2 accepted, 3 failed


def _wolfe_search(objective, w, f0, g0, d, cfg: LBFGSConfig, sdtype):
    """Strong-Wolfe step along ``d`` (Nocedal-Wright 3.5/3.6, bisection
    zoom, both phases bounded by ``max_ls_steps``).  Returns
    ``(t, f_t, g_t, evals, ok, fail_info)``; ``t = 0`` with
    ``ok = False`` when the budget is exhausted without a Wolfe point,
    and ``fail_info = (fail_phase, f_best, t_last, dg0)`` feeds the
    ``ls_stop_reason`` classification."""
    dg0 = tvec.dot(g0, d)
    c1, c2 = cfg.c1, cfg.c2
    one = jnp.ones((), sdtype)
    zero = jnp.zeros((), sdtype)

    def eval_at(t):
        f, g = objective(tvec.axpby(1.0, w, t, d))
        return f, g, tvec.dot(g, d)

    def cond(st: _LS):
        return st.stage < 2

    def body(st: _LS):
        armijo = st.f_t <= f0 + c1 * st.t * dg0
        curv = jnp.abs(st.dg_t) <= -c2 * dg0
        in_bracket = st.stage == 0

        # --- bracket phase decisions (Nocedal-Wright alg 3.5) ---
        # a rise (or a previous-lo dominance) brackets [t_lo, t];
        # a sign change brackets [t, t_lo]; Wolfe accepts outright
        rise = (~armijo) | ((st.it > 0) & (st.f_t >= st.f_lo))
        accept_b = armijo & curv
        swapped = (~rise) & (st.dg_t >= 0)

        b_t_lo = jnp.where(rise, st.t_lo, st.t)
        b_f_lo = jnp.where(rise, st.f_lo, st.f_t)
        b_t_hi = jnp.where(rise, st.t, st.t_lo)
        b_f_hi = jnp.where(rise, st.f_t, st.f_lo)
        to_zoom_b = rise | swapped

        # --- zoom phase decisions (alg 3.6, bisection trial) ---
        z_rise = (~armijo) | (st.f_t >= st.f_lo)
        accept_z = armijo & curv
        # on a kept (non-rising) trial whose slope points past lo,
        # hi collapses onto the old lo
        flip = (~z_rise) & (st.dg_t * (st.t_hi - st.t_lo) >= 0)
        z_t_hi = jnp.where(z_rise, st.t, jnp.where(flip, st.t_lo,
                                                   st.t_hi))
        z_f_hi = jnp.where(z_rise, st.f_t, jnp.where(flip, st.f_lo,
                                                     st.f_hi))
        z_t_lo = jnp.where(z_rise, st.t_lo, st.t)
        z_f_lo = jnp.where(z_rise, st.f_lo, st.f_t)

        accept = jnp.where(in_bracket, accept_b, accept_z)
        t_lo = jnp.where(in_bracket, b_t_lo, z_t_lo)
        f_lo = jnp.where(in_bracket, b_f_lo, z_f_lo)
        t_hi = jnp.where(in_bracket, b_t_hi, z_t_hi)
        f_hi = jnp.where(in_bracket, b_f_hi, z_f_hi)
        entering_zoom = in_bracket & to_zoom_b & (~accept)
        stage = jnp.where(
            accept, 2,
            jnp.where(in_bracket & ~to_zoom_b, 0, 1)).astype(jnp.int32)

        # next trial point: bracket grows, zoom bisects
        t_next = jnp.where(
            stage == 0, st.t * cfg.max_step_growth,
            0.5 * (t_lo + t_hi))
        # per-phase iteration budget: the bracket counter carries on
        # into zoom (fresh budget on entry)
        it = jnp.where(entering_zoom, jnp.zeros((), jnp.int32),
                       st.it + 1)
        exhausted = (st.it + 1 >= cfg.max_ls_steps) & (~accept) & \
            (stage == st.stage) & (~entering_zoom)
        # failure keeps its phase: 3 = bracket exhausted, 4 = zoom
        # exhausted (the ls_stop_reason split needs to know which)
        stage = jnp.where(exhausted, 3 + st.stage, stage)

        do_eval = stage < 2
        f_n, g_n, dg_n = lax.cond(
            do_eval, lambda: eval_at(t_next),
            lambda: (st.f_t, st.g_t, st.dg_t))
        return _LS(t=jnp.where(do_eval, t_next, st.t),
                   f_t=f_n, g_t=g_n, dg_t=dg_n,
                   t_lo=t_lo, f_lo=f_lo,
                   t_hi=t_hi, f_hi=f_hi, it=it,
                   evals=st.evals + do_eval.astype(jnp.int32),
                   stage=stage)

    f1, g1, dg1 = eval_at(one)
    init = _LS(t=one, f_t=f1, g_t=g1, dg_t=dg1,
               t_lo=zero, f_lo=f0,
               t_hi=zero, f_hi=f0,
               it=jnp.zeros((), jnp.int32),
               evals=jnp.ones((), jnp.int32),
               stage=jnp.zeros((), jnp.int32))
    out = lax.while_loop(cond, body, init)
    ok = out.stage == 2
    t = jnp.where(ok, out.t, zero)
    # failure diagnostics for the ls_stop_reason split: which phase
    # exhausted (1 bracket / 2 zoom / 0 none), the best objective any
    # trial reached (f_lo tracks the running "lo" endpoint), the last
    # trial's step, and the initial directional derivative
    fail_phase = jnp.maximum(out.stage - 2, 0)
    return t, out.f_t, out.g_t, out.evals, ok, \
        (fail_phase, out.f_lo, out.t, dg0)


class _Outer(NamedTuple):
    w: Any
    f: jax.Array
    g: Any
    ring: _Ring
    it: jax.Array
    done: jax.Array
    converged: jax.Array
    ls_failed: jax.Array
    ls_reason: jax.Array
    aborted: jax.Array
    hist: jax.Array
    evals: jax.Array


def run_lbfgs(objective: ObjectiveFn, w0: Any,
              config: LBFGSConfig = LBFGSConfig(), *,
              telemetry_cb: Callable | None = None) -> LBFGSResult:
    """Minimize ``objective(w) -> (f, g)`` from ``w0`` — one compiled
    program; jit the call (the api layer does).

    ``telemetry_cb`` (opt-in live streaming, same contract as
    ``core.agd.run_agd``): called via ``jax.debug.callback`` once per
    outer iteration with ``(it, loss, accepted)`` — ``accepted=False``
    marks a failed line search's terminal pass (not an executed
    iteration; the host side filters it).  Default ``None`` traces the
    identical program as before."""
    cfg = config
    m = int(cfg.num_corrections)
    if m < 1:
        raise ValueError("num_corrections must be >= 1")

    sdtype = _carry_dtype(w0)
    objective = _pin_objective(objective, w0, sdtype)
    f0, g0 = objective(w0)
    hist0 = jnp.full((cfg.num_iterations + 1,), jnp.nan, sdtype)
    hist0 = hist0.at[0].set(f0)

    def cond(st: _Outer):
        return (~st.done) & (st.it < cfg.num_iterations)

    def body(st: _Outer):
        d = tvec.scale(-1.0, _two_loop(st.g, st.ring))
        # a non-descent direction (stale curvature) falls back to
        # steepest descent — the standard safeguard
        descent = tvec.dot(st.g, d) < 0
        d = jax.tree_util.tree_map(
            lambda di, gi: jnp.where(descent, di, -gi), d, st.g)
        t, f_n, g_n, evals, ok, ls_info = _wolfe_search(
            objective, st.w, st.f, st.g, d, cfg, sdtype)
        w_n = tvec.axpby(1.0, st.w, t, d)
        s = tvec.sub(w_n, st.w)
        y = tvec.sub(g_n, st.g)
        # positive-definiteness safeguard on the new pair
        sy = tvec.dot(s, y)
        pair_ok = ok & (sy > 1e-10 * tvec.norm(s) * tvec.norm(y))
        ring = _ring_push(st.ring, s, y, pair_ok)

        non_finite = ~jnp.isfinite(f_n)
        keep = ok & (~non_finite)
        improv = (st.f - f_n) / jnp.maximum(
            jnp.maximum(jnp.abs(st.f), jnp.abs(f_n)), 1.0)
        conv_tol = keep & (improv <= cfg.convergence_tol)
        # the grad stop judges the ACCEPTED point only — a failed line
        # search must never flip converged on a discarded trial's g
        conv_grad = keep & (cfg.grad_tol > 0) & \
            (tvec.norm(g_n) < cfg.grad_tol)
        converged = conv_tol | conv_grad
        failed = ~ok
        done = converged | failed | non_finite
        # classify the failure (module-level LS_STOP_* docs): "noise
        # floor" = no trial improved f beyond the carry dtype's
        # resolution AND the first-order expected decrease at the last
        # trial step was below it too — anything else is a genuine
        # bracket/zoom exhaustion mid-descent (worth investigating on a
        # smooth convex problem)
        fail_phase, f_best, t_last, dg0 = ls_info
        tol_f = 32 * jnp.finfo(sdtype).eps * jnp.maximum(
            jnp.abs(st.f), 1.0)
        at_noise = ((st.f - f_best) <= tol_f) & \
            (jnp.abs(dg0) * jnp.abs(t_last) <= tol_f)
        reason = jnp.where(
            failed,
            jnp.where(at_noise, LS_STOP_NOISE_FLOOR, fail_phase),
            LS_STOP_NONE).astype(jnp.int32)

        # only accepted steps count as iterations, so the contract
        # "hist[:num_iters + 1] is finite" survives a failing last step
        it_n = st.it + keep.astype(st.it.dtype)
        w_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(keep, a, b), w_n, st.w)
        g_out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(keep, a, b), g_n, st.g)
        f_out = jnp.where(keep, f_n, st.f)
        hist = st.hist.at[it_n].set(jnp.where(keep, f_n,
                                              st.hist[it_n]))
        if telemetry_cb is not None:
            jax.debug.callback(telemetry_cb, it=it_n, loss=f_out,
                               accepted=keep)
        return _Outer(w=w_out, f=f_out, g=g_out, ring=ring, it=it_n,
                      done=done,
                      converged=st.converged | converged,
                      ls_failed=st.ls_failed | failed,
                      ls_reason=jnp.where(st.ls_failed, st.ls_reason,
                                          reason),
                      aborted=st.aborted | non_finite,
                      hist=hist, evals=st.evals + evals)

    init = _Outer(
        w=w0, f=f0, g=g0, ring=_ring_init(w0, m, sdtype),
        it=jnp.zeros((), jnp.int32),
        done=~jnp.isfinite(f0),
        converged=jnp.zeros((), bool),
        ls_failed=jnp.zeros((), bool),
        ls_reason=jnp.zeros((), jnp.int32),
        aborted=~jnp.isfinite(f0),
        hist=hist0,
        evals=jnp.ones((), jnp.int32))
    out = lax.while_loop(cond, body, init)
    return LBFGSResult(
        weights=out.w, loss_history=out.hist, num_iters=out.it,
        converged=out.converged, ls_failed=out.ls_failed,
        aborted_non_finite=out.aborted, grad_norm=tvec.norm(out.g),
        num_fn_evals=out.evals, ls_stop_reason=out.ls_reason)


# ---------------------------------------------------------------------------
# OWL-QN (Orthant-Wise Limited-memory Quasi-Newton, Andrew & Gao 2007):
# L-BFGS for L1-regularized objectives F(w) = f(w) + l1·‖w‖₁.  This is
# the algorithm Spark adopted AFTER 1.3 (Breeze OWLQN under
# ml.LogisticRegression's elasticNetParam) to lift exactly the
# no-L1-in-LBFGS limitation this module documents — provided here so the
# quasi-Newton member covers the reference's full updater menu
# (BASELINE config 3 pairs hinge with L1Updater).
#
# Structure vs run_lbfgs: the same ring-buffer two-loop recursion over
# curvature pairs of the SMOOTH part's gradients, but
# - search direction comes from the PSEUDO-gradient of F (the minimal-
#   norm subgradient), then is projected to its descent orthant;
# - the line search is backtracking-Armijo with an ORTHANT projection:
#   each trial point is clipped to the orthant ξ chosen at the iterate
#   (sign(w), or sign(-pseudo-grad) at zeros), which is what produces
#   EXACT zeros;
# - convergence is the same relative-improvement test, on F.
#
# Correctness oracle: prox-AGD (core/agd.py with L1Prox) minimizes the
# identical convex objective — tests pin final-F parity between the two
# (tests/test_lbfgs.py::TestOWLQN).


def _pseudo_gradient(w, g, l1):
    """Leafwise minimal-norm subgradient of f + l1·‖·‖₁ at w."""
    def leaf(wi, gi):
        pos = gi + l1
        neg = gi - l1
        at_zero = jnp.where(pos < 0, pos, jnp.where(neg > 0, neg, 0.0))
        return jnp.where(wi > 0, pos, jnp.where(wi < 0, neg, at_zero))

    return tvec.tmap(leaf, w, g)


class _OWL(NamedTuple):
    w: Any
    big_f: jax.Array  # F = f + l1·‖w‖₁ (+ smooth extra)
    g: Any  # smooth-part gradient
    ring: _Ring
    it: jax.Array
    done: jax.Array
    converged: jax.Array
    ls_failed: jax.Array
    ls_reason: jax.Array
    aborted: jax.Array
    hist: jax.Array
    evals: jax.Array


def run_owlqn(objective_smooth: ObjectiveFn, w0: Any, l1_reg: float,
              config: LBFGSConfig = LBFGSConfig(), *,
              telemetry_cb: Callable | None = None) -> LBFGSResult:
    """Minimize ``objective_smooth(w) -> (f, g)`` plus
    ``l1_reg·‖w‖₁`` from ``w0`` — one compiled program.  The smooth
    callable may already fold in a differentiable (L2) penalty, so an
    elastic net is ``make_objective``'s smooth part + this ``l1_reg``.

    ``loss_history`` entries are the FULL objective F (smooth + L1),
    comparable to prox-AGD's ``f + reg_value`` accounting on the same
    problem.  ``num_fn_evals`` counts smooth evaluations."""
    cfg = config
    m = int(cfg.num_corrections)
    if m < 1:
        raise ValueError("num_corrections must be >= 1")
    if l1_reg < 0:
        raise ValueError("l1_reg must be >= 0")

    sdtype = _carry_dtype(w0)
    objective_smooth = _pin_objective(objective_smooth, w0, sdtype)
    f0, g0 = objective_smooth(w0)
    l1 = jnp.asarray(l1_reg, sdtype)
    big_f0 = f0 + l1 * tvec.l1_norm(w0)
    hist0 = jnp.full((cfg.num_iterations + 1,), jnp.nan, sdtype)
    hist0 = hist0.at[0].set(big_f0)

    def cond(st: _OWL):
        return (~st.done) & (st.it < cfg.num_iterations)

    def body(st: _OWL):
        pg = _pseudo_gradient(st.w, st.g, l1)
        d = tvec.scale(-1.0, _two_loop(pg, st.ring))
        # orthant alignment: drop components whose quasi-Newton sign
        # disagrees with steepest descent (Andrew & Gao eq. "p = π(d;
        # -pseudo-grad)"); fall back to -pg if nothing survives
        d = tvec.tmap(lambda di, pgi: jnp.where(di * pgi < 0, di, 0.0),
                      d, pg)
        deg = tvec.dot(d, d) == 0
        d = jax.tree_util.tree_map(
            lambda di, pgi: jnp.where(deg, -pgi, di), d, pg)
        # the orthant each trial is clipped to
        xi = tvec.tmap(
            lambda wi, pgi: jnp.where(wi != 0, jnp.sign(wi),
                                      jnp.sign(-pgi)), st.w, pg)

        def trial(t):
            w_t = tvec.tmap(
                lambda wi, di, xii: jnp.where(
                    (wi + t * di) * xii > 0, wi + t * di, 0.0),
                st.w, d, xi)
            f_t, g_t = objective_smooth(w_t)
            return w_t, f_t, f_t + l1 * tvec.l1_norm(w_t), g_t

        # backtracking Armijo on F with the pseudo-gradient directional
        # derivative (Andrew & Gao's accept rule), halving t
        def ls_cond(carry):
            t, _, _, big_f_t, _, k, accept = carry
            return (~accept) & (k < cfg.max_ls_steps)

        def ls_body(carry):
            t, _, _, _, _, k, _ = carry
            w_t, f_t, big_f_t, g_t = trial(t)
            # Armijo via the PROJECTED step (w_t - w), not t·d: the
            # orthant clip can shorten the step
            gain = tvec.dot(pg, tvec.sub(w_t, st.w))
            accept = big_f_t <= st.big_f + cfg.c1 * gain
            accept = accept & jnp.isfinite(big_f_t)
            t_next = jnp.where(accept, t, t * 0.5)
            return (t_next, w_t, f_t, big_f_t, g_t, k + 1, accept)

        w1, f1, bf1, g1 = trial(jnp.ones((), sdtype))
        gain1 = tvec.dot(pg, tvec.sub(w1, st.w))
        acc1 = (bf1 <= st.big_f + cfg.c1 * gain1) & jnp.isfinite(bf1)
        t, w_n, f_n, big_f_n, g_n, ls_k, ok = lax.while_loop(
            ls_cond, ls_body,
            (jnp.where(acc1, 1.0, 0.5).astype(sdtype), w1, f1, bf1, g1,
             jnp.ones((), jnp.int32), acc1))

        non_finite = ~jnp.isfinite(big_f_n)
        keep = ok & (~non_finite)
        # failure classification (LS_STOP_* docs): OWL-QN's search is
        # backtracking-Armijo, so a budget exhaustion is either the
        # noise floor (last, smallest-step trial changed F by less than
        # the dtype's resolution and expected no more) or a genuine
        # Armijo exhaustion mid-descent
        tol_f = 32 * jnp.finfo(sdtype).eps * jnp.maximum(
            jnp.abs(st.big_f), 1.0)
        last_gain = tvec.dot(pg, tvec.sub(w_n, st.w))
        at_noise = (jnp.abs(big_f_n - st.big_f) <= tol_f) & \
            (jnp.abs(last_gain) <= tol_f)
        reason = jnp.where(
            ~ok, jnp.where(at_noise, LS_STOP_NOISE_FLOOR,
                           LS_STOP_ARMIJO),
            LS_STOP_NONE).astype(jnp.int32)
        s = tvec.sub(w_n, st.w)
        y = tvec.sub(g_n, st.g)  # raw smooth gradients (Andrew & Gao)
        sy = tvec.dot(s, y)
        pair_ok = keep & (sy > 1e-10 * tvec.norm(s) * tvec.norm(y))
        ring = _ring_push(st.ring, s, y, pair_ok)

        improv = (st.big_f - big_f_n) / jnp.maximum(
            jnp.maximum(jnp.abs(st.big_f), jnp.abs(big_f_n)), 1.0)
        conv = keep & (improv <= cfg.convergence_tol)
        conv_grad = keep & (cfg.grad_tol > 0) & \
            (tvec.norm(_pseudo_gradient(w_n, g_n, l1)) < cfg.grad_tol)
        converged = conv | conv_grad
        done = converged | (~ok) | non_finite

        it_n = st.it + keep.astype(st.it.dtype)
        pick = lambda a, b: jax.tree_util.tree_map(
            lambda x, yv: jnp.where(keep, x, yv), a, b)
        hist = st.hist.at[it_n].set(jnp.where(keep, big_f_n,
                                              st.hist[it_n]))
        if telemetry_cb is not None:
            jax.debug.callback(telemetry_cb, it=it_n,
                               loss=jnp.where(keep, big_f_n, st.big_f),
                               accepted=keep)
        return _OWL(w=pick(w_n, st.w),
                    big_f=jnp.where(keep, big_f_n, st.big_f),
                    g=pick(g_n, st.g), ring=ring, it=it_n, done=done,
                    converged=st.converged | converged,
                    ls_failed=st.ls_failed | (~ok),
                    ls_reason=jnp.where(st.ls_failed, st.ls_reason,
                                        reason),
                    aborted=st.aborted | non_finite,
                    hist=hist, evals=st.evals + ls_k)

    init = _OWL(
        w=w0, big_f=big_f0, g=g0,
        ring=_ring_init(w0, m, sdtype),
        it=jnp.zeros((), jnp.int32), done=~jnp.isfinite(big_f0),
        converged=jnp.zeros((), bool), ls_failed=jnp.zeros((), bool),
        ls_reason=jnp.zeros((), jnp.int32),
        aborted=~jnp.isfinite(big_f0), hist=hist0,
        evals=jnp.ones((), jnp.int32))
    out = lax.while_loop(cond, body, init)
    return LBFGSResult(
        weights=out.w, loss_history=out.hist, num_iters=out.it,
        converged=out.converged, ls_failed=out.ls_failed,
        ls_stop_reason=out.ls_reason,
        aborted_non_finite=out.aborted,
        grad_norm=tvec.norm(_pseudo_gradient(out.w, out.g,
                                             jnp.asarray(l1_reg,
                                                         sdtype))),
        num_fn_evals=out.evals)
