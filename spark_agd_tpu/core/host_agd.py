"""Host-orchestrated AGD: the streaming twin of the fused loop.

Same recurrences as ``core.agd`` (and the same reference citations — see
that module's docstring), but with the outer/inner loops in Python and only
the math on device.  This is the driver shape the reference itself has
(SURVEY §3.1), retained for exactly one reason: a *streamed* smooth
function (``data.streaming``) contains a host loop and cannot live inside
``lax.while_loop``.  Control scalars sync to the host once per trial — for
macro-batch workloads the stream dominates, so the syncs are noise.

Use ``core.agd.run_agd`` whenever the data fits on-device; this driver
exists for the 1B-row regime.  Semantics parity between the two is pinned
by ``tests/test_data_layer.py`` (streamed-vs-in-memory) and
``tests/test_checkpoint.py`` (kill/resume trajectories).
"""
# graftlint: disable-file=host-sync -- host-orchestrated driver by
# design: streamed smooth functions cannot live inside lax.while_loop,
# so control scalars sync once per trial (see module docstring)

from __future__ import annotations

import math
from typing import Any, Callable, List, NamedTuple, Tuple

import numpy as np

from . import tvec
from .agd import AGDConfig, AGDWarmState


class HostAGDResult(NamedTuple):
    weights: Any
    loss_history: np.ndarray
    num_iters: int
    aborted_non_finite: bool
    final_l: float
    num_backtracks: int
    num_restarts: int
    # continuation carry (mirrors core.agd.AGDResult; utils.checkpoint)
    final_z: Any = None
    final_theta: float = math.inf
    final_bts: bool = True
    # stopped by its own criteria (not the cap, not an abort) — the
    # fused loop's `converged` semantics (core/agd.py)
    converged: bool = False


def run_agd_host(
    smooth: Callable,
    prox: Callable,
    reg_value: Callable,
    w0: Any,
    config: AGDConfig,
    *,
    smooth_loss: Callable | None = None,
    warm=None,
    on_iteration: Callable | None = None,
) -> HostAGDResult:
    """``warm`` is a ``core.agd.AGDWarmState`` (or any object with the same
    fields) to continue a checkpointed run; ``on_iteration(state_dict)`` is
    called after every outer iteration with the full continuation carry plus
    that iteration's loss — the checkpoint/metrics hook (SURVEY §5)."""
    cfg = config
    if cfg.loss_mode not in ("x", "x_strict", "y"):
        raise ValueError(f"unknown loss_mode {cfg.loss_mode!r}")
    if warm is None:
        warm = AGDWarmState.initial(w0, cfg)
    x, z = warm.x, warm.z
    theta = float(warm.theta)
    big_l = float(warm.big_l)
    bts = bool(warm.bts)
    prior_iters = int(warm.prior_iters)
    loss_hist: List[float] = []
    n_bt = 0
    n_restart = 0
    aborted = False
    stopped_by_criteria = False
    backtracking = cfg.beta < 1.0

    for n_iter in range(prior_iters + 1, prior_iters + cfg.num_iterations + 1):
        x_old, z_old = x, z
        l_old = big_l
        big_l = big_l * cfg.alpha
        theta_old = theta

        f_y = 0.0
        g_y = None
        y = x
        f_x_reuse = None
        # do-while, like the fused loop's unconditional body(init): the
        # first trial always runs, and max_backtracks total trials run when
        # every trial rejects — identical to core.agd's body(init) +
        # ``while n_bt < max_backtracks`` structure.
        for _ in range(max(1, cfg.max_backtracks)):
            theta = 2.0 / (1.0 + math.sqrt(
                1.0 + 4.0 * (big_l / l_old) / (theta_old * theta_old)))
            y = tvec.axpby(1.0 - theta, x_old, theta, z_old)
            f_y_d, g_y = smooth(y)
            f_y = float(f_y_d)
            step = 1.0 / (theta * big_l)
            z = prox(z_old, g_y, step)[0]
            x = tvec.axpby(1.0 - theta, x_old, theta, z)

            if not backtracking:
                f_x_reuse = None
                break

            xy = tvec.sub(x, y)
            xy_sq = float(tvec.sq_norm(xy))
            if xy_sq == 0.0 or not math.isfinite(f_y):
                f_x_reuse = f_y  # x == y exactly (or aborting anyway)
                break

            f_x_d, g_x = smooth(x)
            f_x = float(f_x_d)
            f_x_reuse = f_x
            if bts:
                q_x = f_y + float(tvec.dot(xy, g_y)) + 0.5 * big_l * xy_sq
                local_l = big_l + 2.0 * max(f_x - q_x, 0.0) / xy_sq
                bts = (abs(f_y - f_x)
                       >= cfg.backtrack_tol * max(abs(f_x), abs(f_y)))
            else:
                local_l = 2.0 * float(tvec.dot(xy, tvec.sub(g_x, g_y))) \
                    / xy_sq

            if local_l <= big_l or big_l >= cfg.l_exact:
                break

            n_bt += 1
            if not math.isinf(local_l):
                big_l = min(cfg.l_exact, local_l)
            else:
                local_l = big_l
            big_l = min(cfg.l_exact, max(local_l, big_l / cfg.beta))

        # loss history (same modes as the fused loop)
        if cfg.loss_mode == "y":
            loss_hist.append(f_y + float(reg_value(y)))
        elif cfg.loss_mode == "x_strict":
            loss_hist.append(float(smooth(x)[0]) + float(reg_value(x)))
        else:  # 'x'
            if f_x_reuse is None:
                ls = smooth_loss or (lambda w: smooth(w)[0])
                f_x_reuse = float(ls(x))
            loss_hist.append(f_x_reuse + float(reg_value(x)))

        if not math.isfinite(f_y):
            aborted = True
            if on_iteration is not None:
                on_iteration(_carry(x, z, theta, big_l, bts, n_iter,
                                    loss_hist[-1], aborted=True,
                                    stopped=True, last=True))
            break

        stop = False
        norm_x = float(tvec.norm(x))
        norm_dx = float(tvec.norm(tvec.sub(x, x_old)))
        if norm_dx == 0.0 and n_iter > 1:
            stop = True
        elif norm_dx < cfg.convergence_tol * max(norm_x, 1.0):
            stop = True
        elif cfg.may_restart \
                and float(tvec.dot(g_y, tvec.sub(x, x_old))) > 0:
            z = x
            theta = math.inf
            bts = True
            n_restart += 1

        if on_iteration is not None:
            last = n_iter == prior_iters + cfg.num_iterations
            on_iteration(_carry(x, z, theta, big_l, bts, n_iter,
                                loss_hist[-1], stopped=stop, last=last))
        if stop:
            stopped_by_criteria = True
            break

    return HostAGDResult(
        weights=x, loss_history=np.asarray(loss_hist),
        num_iters=len(loss_hist), aborted_non_finite=aborted,
        final_l=big_l, num_backtracks=n_bt, num_restarts=n_restart,
        final_z=z, final_theta=theta, final_bts=bts,
        converged=stopped_by_criteria)


def _carry(x, z, theta, big_l, bts, n_iter, loss, aborted=False,
           stopped=False, last=False) -> dict:
    """The on_iteration payload: the exact continuation carry + metrics.
    ``stopped`` marks the converged final iteration; ``aborted`` the
    non-finite one (which also stops); ``last`` the iteration-cap exit —
    one of the three is always true on a run's final callback."""
    return dict(x=x, z=z, theta=theta, big_l=big_l, bts=bts,
                prior_iters=n_iter, loss=loss, aborted=aborted,
                stopped=stopped or aborted, last=last or aborted)


# ---------------------------------------------------------------------------
# Multi-lane host driver: K independent AGD trajectories in lock-step over
# ONE shared multi-lane smooth — the streamed regularization path.  A solo
# host sweep costs K full stream reads per evaluation; this reads the
# stream once per trial for ALL lanes (data.streaming.
# make_streaming_eval_multi fuses the K margin products per macro-batch).
# Semantics: each lane's recurrence is EXACTLY run_agd_host's — lanes that
# accept/stop early are frozen by masks while the lock-step continues, and
# since evaluations are pure, the extra (masked-out) evaluations cannot
# change any lane's trajectory.  Pinned per-lane against the solo driver
# by tests/test_host_multi.py.
# ---------------------------------------------------------------------------


class HostAGDMultiResult(NamedTuple):
    """Batched result: every per-lane field carries a leading K axis
    (the host twin of a batched ``core.agd.AGDResult`` from a sweep),
    EXCEPT ``loss_history`` whose lane axis is SECOND:
    ``loss_history[:, k][:num_iters[k]]`` is lane k's executed
    history."""

    weights: Any              # stacked (K, ...) pytree
    loss_history: np.ndarray  # (executed_iters, K) -> indexed [i, k];
    #                           first axis = GLOBALLY executed
    #                           iterations (max over lanes, <= the
    #                           configured num_iterations when every
    #                           lane stops early)
    num_iters: np.ndarray     # (K,)
    aborted_non_finite: np.ndarray  # (K,) bool
    final_l: np.ndarray       # (K,)
    num_backtracks: np.ndarray  # (K,)
    num_restarts: np.ndarray  # (K,)
    final_z: Any = None
    final_theta: Any = None   # (K,)
    final_bts: Any = None     # (K,) bool
    converged: Any = None     # (K,) bool


def _bc(a, leaf):
    """Broadcast a per-lane (K,) host array against a stacked leaf."""
    import jax.numpy as jnp

    return jnp.asarray(a).reshape((-1,) + (1,) * (leaf.ndim - 1))


def _axpby_lanes(a, X, b, Y):
    """Per-lane axpby on stacked pytrees: a,b are (K,) arrays."""
    return tvec.tmap(lambda u, v: _bc(a, u) * u + _bc(b, v) * v, X, Y)


def _where_lanes(m, A, B):
    """Per-lane select on stacked pytrees: m is a (K,) bool array."""
    import jax.numpy as jnp

    return tvec.tmap(
        lambda u, v: jnp.where(_bc(m, u) != 0, u, v), A, B)


def _dot_lanes(A, B):
    """Per-lane <A, B>: (K,) NumPy array."""
    import jax
    import jax.numpy as jnp

    leaves_a = jax.tree_util.tree_leaves(A)
    leaves_b = jax.tree_util.tree_leaves(B)
    tot = sum(jnp.sum((u * v).reshape(u.shape[0], -1), axis=1)
              for u, v in zip(leaves_a, leaves_b))
    return np.asarray(tot)


class HostMultiWarm(NamedTuple):
    """Continuation carry for :func:`run_agd_host_multi` — the multi-
    lane twin of ``AGDWarmState`` plus the per-lane stop bookkeeping a
    lock-step resume needs (a lane that converged before the kill must
    STAY stopped; counters continue, not restart)."""

    x: Any                 # stacked (K, ...) pytree
    z: Any
    theta: np.ndarray      # (K,)
    big_l: np.ndarray      # (K,)
    bts: np.ndarray        # (K,) bool
    prior_iters: np.ndarray  # (K,) iterations already executed
    converged: np.ndarray  # (K,) bool — stopped by its own criteria
    aborted: np.ndarray    # (K,) bool
    num_backtracks: np.ndarray  # (K,)
    num_restarts: np.ndarray    # (K,)
    last_loss: np.ndarray  # (K,) last recorded history row — warm
    #                        segments forward-fill stopped lanes with
    #                        THIS (an uninterrupted run repeats the
    #                        converged loss, not NaN)

    @classmethod
    def initial(cls, w0_stacked, config) -> "HostMultiWarm":
        """The iteration-zero carry — defined ONCE (the checkpoint
        layer must not hand-roll its own copy)."""
        import jax
        import jax.numpy as jnp

        k = jax.tree_util.tree_leaves(w0_stacked)[0].shape[0]
        w = jax.tree_util.tree_map(jnp.asarray, w0_stacked)
        return cls(
            x=w, z=w, theta=np.full(k, np.inf),
            big_l=np.full(k, float(config.l0)), bts=np.ones(k, bool),
            prior_iters=np.zeros(k, np.int64),
            converged=np.zeros(k, bool), aborted=np.zeros(k, bool),
            num_backtracks=np.zeros(k, np.int64),
            num_restarts=np.zeros(k, np.int64),
            last_loss=np.full(k, np.nan))


def multi_warm_state(res: "HostAGDMultiResult",
                     prior_iters=0) -> HostMultiWarm:
    """The continuation carry out of a multi-lane result — feed to
    ``run_agd_host_multi(..., warm=...)`` to run the next segment.

    ``prior_iters``: per-lane iterations executed BEFORE the segment
    ``res`` came from (0 for the first continuation; pass the previous
    warm's ``prior_iters`` when chaining — the ``sweep_warm_state``
    convention), so the total accumulates and the ``n_iter > 1``
    exact-zero-step gate keeps making uninterrupted-run decisions."""
    hist = np.asarray(res.loss_history)
    k = len(np.asarray(res.num_iters))
    return HostMultiWarm(
        x=res.weights, z=res.final_z,
        theta=np.asarray(res.final_theta, float),
        big_l=np.asarray(res.final_l, float),
        bts=np.asarray(res.final_bts, bool),
        prior_iters=(np.asarray(prior_iters, np.int64)
                     + np.asarray(res.num_iters, np.int64)),
        converged=np.asarray(res.converged, bool),
        aborted=np.asarray(res.aborted_non_finite, bool),
        num_backtracks=np.asarray(res.num_backtracks, np.int64),
        num_restarts=np.asarray(res.num_restarts, np.int64),
        last_loss=(hist[-1] if hist.shape[0]
                   else np.full(k, np.nan)))


def make_prox_multi(updater, reg_params):
    """Per-lane prox/reg-value pair for a strength grid: jitted vmap of
    the updater over (lane state, lane gradient, lane step, lane reg)."""
    import jax
    import jax.numpy as jnp

    # native dtype (f64 under x64): rounding strengths to f32 would
    # silently fork every lane's trajectory from a solo run at the
    # same (python-float) strength
    regs = jnp.asarray(reg_params)

    @jax.jit
    def prox_multi(Z, G, steps):
        return jax.vmap(
            lambda z, g, s, r: updater.prox(z, g, s, r)[0])(
                # graftlint: disable=constant-capture -- regs is a tiny
                # (n_lanes,) strengths vector embedded deliberately for
                # dtype fidelity (see binding above), not dataset-scale
                Z, G, jnp.asarray(steps), regs)

    @jax.jit
    def reg_value_multi(W):
        return jax.vmap(
            lambda w, r: updater.prox(
                # graftlint: disable=constant-capture -- same tiny
                # deliberate (n_lanes,) strengths constant as prox_multi
                w, tvec.zeros_like(w), 0.0, r)[1])(W, regs)

    return prox_multi, reg_value_multi


def run_agd_host_multi(
    smooth_multi: Callable,
    prox_multi: Callable,
    reg_value_multi: Callable,
    w0_stacked: Any,
    config: AGDConfig,
    *,
    smooth_loss_multi: Callable | None = None,
    warm: HostMultiWarm | None = None,
) -> HostAGDMultiResult:
    """K-lane lock-step twin of :func:`run_agd_host`.

    ``smooth_multi(W_stacked) -> ((K,) losses, stacked grads)`` — e.g.
    ``data.streaming.make_streaming_eval_multi``;
    ``prox_multi(Z, G, steps) -> Z_new`` and
    ``reg_value_multi(W) -> (K,)`` — e.g. :func:`make_prox_multi`.
    ``w0_stacked`` carries the lane axis (same ``w0`` in every lane:
    ``np.broadcast_to``/``jnp.stack`` it).

    ``warm`` (:func:`multi_warm_state`) continues a prior segment:
    converged/aborted lanes stay stopped, counters continue, and the
    returned ``loss_history``/``num_iters`` cover THIS segment only
    (the solo-driver checkpointing convention).
    """
    import jax
    import jax.numpy as jnp

    cfg = config
    if cfg.loss_mode not in ("x", "x_strict", "y"):
        raise ValueError(f"unknown loss_mode {cfg.loss_mode!r}")
    k_lanes = jax.tree_util.tree_leaves(w0_stacked)[0].shape[0]
    if warm is None:
        warm = HostMultiWarm.initial(w0_stacked, cfg)
    x = jax.tree_util.tree_map(jnp.asarray, warm.x)
    z = jax.tree_util.tree_map(jnp.asarray, warm.z)
    theta = np.asarray(warm.theta, float).copy()
    big_l = np.asarray(warm.big_l, float).copy()
    bts = np.asarray(warm.bts, bool).copy()
    n_bt = np.asarray(warm.num_backtracks, np.int64).copy()
    n_restart = np.asarray(warm.num_restarts, np.int64).copy()
    aborted = np.asarray(warm.aborted, bool).copy()
    stopped_by_criteria = np.asarray(warm.converged, bool).copy()
    it_base = np.asarray(warm.prior_iters, np.int64).copy()
    prev_fill = np.asarray(warm.last_loss, float).copy()
    active = ~(aborted | stopped_by_criteria)
    num_iters = np.zeros(k_lanes, np.int64)
    hist_rows: List[np.ndarray] = []
    backtracking = cfg.beta < 1.0

    for n_iter in range(1, cfg.num_iterations + 1):
        if not active.any():
            break
        x_old, z_old = x, z
        l_old = big_l.copy()
        big_l = np.where(active, big_l * cfg.alpha, big_l)
        theta_old = theta.copy()

        f_y = np.zeros(k_lanes)
        g_y = None
        y = x
        f_x_reuse = np.full(k_lanes, np.nan)
        have_f_x = np.zeros(k_lanes, bool)
        pending = active.copy()
        for _ in range(max(1, cfg.max_backtracks)):
            theta_try = 2.0 / (1.0 + np.sqrt(
                1.0 + 4.0 * (big_l / l_old) / (theta_old * theta_old)))
            theta = np.where(pending, theta_try, theta)
            y_try = _axpby_lanes(1.0 - theta, x_old, theta, z_old)
            y = _where_lanes(pending, y_try, y)
            f_y_all, g_y_all = smooth_multi(y)
            f_y = np.where(pending, np.asarray(f_y_all), f_y)
            g_y = (g_y_all if g_y is None
                   else _where_lanes(pending, g_y_all, g_y))
            step = 1.0 / (theta * big_l)
            z_try = prox_multi(z_old, g_y, step)
            z = _where_lanes(pending, z_try, z)
            x_try = _axpby_lanes(1.0 - theta, x_old, theta, z)
            x = _where_lanes(pending, x_try, x)

            if not backtracking:
                have_f_x[:] = False
                break

            xy = tvec.sub(x, y)
            xy_sq = _dot_lanes(xy, xy)
            degenerate = pending & (
                (xy_sq == 0.0) | ~np.isfinite(f_y))
            f_x_reuse = np.where(degenerate, f_y, f_x_reuse)
            have_f_x = have_f_x | degenerate
            pending = pending & ~degenerate
            if not pending.any():
                break

            f_x_all, g_x_all = smooth_multi(x)
            f_x = np.asarray(f_x_all)
            f_x_reuse = np.where(pending, f_x, f_x_reuse)
            have_f_x = have_f_x | pending
            xy_sq_safe = np.where(xy_sq > 0, xy_sq, 1.0)
            q_x = (f_y + _dot_lanes(xy, g_y)
                   + 0.5 * big_l * xy_sq_safe)
            local_simple = big_l + 2.0 * np.maximum(f_x - q_x, 0.0) \
                / xy_sq_safe
            local_curv = 2.0 * _dot_lanes(
                xy, tvec.sub(g_x_all, g_y)) / xy_sq_safe
            # local_l uses the CURRENT bts (simple vs curvature
            # estimate); bts then switches only for lanes that were in
            # simple mode (the solo driver's `if bts: ... bts = ...`)
            local_l = np.where(bts, local_simple, local_curv)
            bts_next = (np.abs(f_y - f_x)
                        >= cfg.backtrack_tol
                        * np.maximum(np.abs(f_x), np.abs(f_y)))
            bts = np.where(pending & bts, bts_next, bts)

            accept = pending & ((local_l <= big_l)
                                | (big_l >= cfg.l_exact))
            reject = pending & ~accept
            n_bt += reject.astype(np.int64)
            # the solo loop's ∞-localL dance, with Python's min/max
            # NaN semantics mirrored exactly (np.minimum propagates
            # NaN where Python's min(l_exact, nan) returns l_exact —
            # the r3 review caught the divergence): +inf keeps big_l
            # then grows by 1/beta; NaN resolves to l_exact; finite
            # takes min(l_exact, local) then max with bl1/beta.
            linf = np.isinf(local_l)
            lnan = np.isnan(local_l)
            bl1 = np.where(
                linf, big_l,
                np.where(lnan, cfg.l_exact,
                         np.minimum(cfg.l_exact, local_l)))
            leff = np.where(linf, big_l, local_l)
            bl2 = np.where(
                lnan, cfg.l_exact,
                np.minimum(cfg.l_exact,
                           np.maximum(leff, bl1 / cfg.beta)))
            big_l = np.where(reject, bl2, big_l)
            pending = reject
            if not pending.any():
                break

        # loss history (same modes as the solo driver), active lanes only
        if cfg.loss_mode == "y":
            loss_row = f_y + np.asarray(reg_value_multi(y))
        elif cfg.loss_mode == "x_strict":
            loss_row = (np.asarray(smooth_multi(x)[0])
                        + np.asarray(reg_value_multi(x)))
        else:  # 'x'
            need = active & ~have_f_x
            if need.any():
                ls = smooth_loss_multi or (
                    lambda W: smooth_multi(W)[0])
                f_fresh = np.asarray(ls(x))
                f_x_reuse = np.where(have_f_x, f_x_reuse, f_fresh)
            loss_row = f_x_reuse + np.asarray(reg_value_multi(x))
        # stopped lanes forward-fill their last recorded loss — across
        # warm-segment boundaries too (prev_fill carries it), so a
        # checkpointed history equals the uninterrupted one
        prev = hist_rows[-1] if hist_rows else prev_fill
        hist_rows.append(np.where(active, loss_row, prev))
        num_iters += active.astype(np.int64)

        abort_now = active & ~np.isfinite(f_y)
        aborted |= abort_now
        active = active & ~abort_now

        dx = tvec.sub(x, x_old)
        norm_dx = np.sqrt(np.maximum(_dot_lanes(dx, dx), 0.0))
        norm_x = np.sqrt(np.maximum(_dot_lanes(x, x), 0.0))
        # per-lane TOTAL iteration count (warm segments accumulate) for
        # the exact-zero-step nIter>1 gate
        it_count = it_base + num_iters
        stop = active & (
            ((norm_dx == 0.0) & (it_count > 1))
            | (norm_dx < cfg.convergence_tol * np.maximum(norm_x, 1.0)))
        stopped_by_criteria |= stop
        active = active & ~stop
        if cfg.may_restart:
            restart = active & (_dot_lanes(g_y, dx) > 0)
            if restart.any():
                z = _where_lanes(restart, x, z)
                theta = np.where(restart, np.inf, theta)
                bts = np.where(restart, True, bts)
                n_restart += restart.astype(np.int64)

    return HostAGDMultiResult(
        weights=x,
        loss_history=(np.stack(hist_rows)
                      if hist_rows else np.zeros((0, k_lanes))),
        num_iters=num_iters, aborted_non_finite=aborted,
        final_l=big_l, num_backtracks=n_bt, num_restarts=n_restart,
        final_z=z, final_theta=theta, final_bts=bts,
        converged=stopped_by_criteria)
