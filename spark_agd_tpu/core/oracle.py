"""Plain-NumPy TFOCS-AT oracle — the executable specification of AGD.

The reference's only correctness spec is "final loss within 2% of MLlib GD"
(reference Suite:88-91).  SURVEY §7 step 2 calls for something much stronger:
a driver-style NumPy implementation of the exact recurrences of
``AcceleratedGradientDescent.run`` (reference
``AcceleratedGradientDescent.scala:224-332``) that the compiled TPU
implementation must match *step by step* in float64.  This file is that
oracle.  It is deliberately written as a slow, obvious, sequential Python
loop over flat NumPy vectors — no JAX — so that any disagreement with the
compiled path localises the bug to the compiled path.

Semantics covered (each with its reference citation):

- Auslender–Teboulle acceleration with ``theta = +inf`` first-iteration
  identity (``:226, :248``)
- backtracking line search with the simple/curvature estimator switch at
  tolerance 1e-10 (``:261-293``, switch ``:272-279``, tol ``:235``)
- the L-update dance including the infinite-localL quirk (``:285-292``)
- loss history at x: ``f(x) + reg(x)`` — a third distributed pass in the
  reference (``:302-307``)
- NaN/Inf loss guard (``:309-312``)
- convergence: exact-zero step only counts after iteration 1; relative
  tolerance vs ``max(‖x‖, 1)`` (``:314-324``)
- O'Donoghue–Candes gradient-test restart (``:326-331``)

The oracle counts ``smooth`` evaluations so tests can also pin the
2-3-passes-per-iteration cost shape (SURVEY §3.1).
"""
# graftlint: disable-file=host-sync -- pure-NumPy f64 reference oracle:
# every value is already on the host; there is no device to sync with

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, List, Tuple

import numpy as np


@dataclass
class OracleResult:
    weights: np.ndarray
    loss_history: List[float]
    num_smooth_calls: int
    num_backtracks: int
    num_restarts: int
    aborted_non_finite: bool


def run_oracle(
    smooth: Callable[[np.ndarray], Tuple[float, np.ndarray]],
    prox: Callable[[np.ndarray, np.ndarray, float], Tuple[np.ndarray, float]],
    w0: np.ndarray,
    *,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    backtrack_tol: float = 1e-10,
    max_backtracks: int = 100,
) -> OracleResult:
    """Run the TFOCS-AT recurrence exactly as the reference driver does.

    ``smooth(w) -> (mean_loss, mean_grad)``; ``prox(w, g, step) ->
    (w_new, reg_value)`` with the ``step = 0`` identity.  ``max_backtracks``
    is a safety bound absent from the reference (whose inner ``while(true)``
    can spin forever on NaN losses); it is set high enough to never trigger
    on finite data.
    """
    calls = {"n": 0}

    def smooth_counted(w):
        calls["n"] += 1
        return smooth(w)

    x = np.array(w0, dtype=np.float64, copy=True)
    z = x
    theta = math.inf
    L = float(l0)
    backtrack_simple = True
    loss_history: List[float] = []
    n_backtracks = 0
    n_restarts = 0
    aborted = False

    for n_iter in range(1, num_iterations + 1):
        x_old, z_old = x, z
        L_old = L
        L = L * alpha
        theta_old = theta

        f_y = 0.0
        g_y = np.zeros_like(x)
        y = x
        for bt in range(max_backtracks):
            theta = 2.0 / (1.0 + math.sqrt(
                1.0 + 4.0 * (L / L_old) / (theta_old * theta_old)))
            y = (1.0 - theta) * x_old + theta * z_old
            f_y, g_y = smooth_counted(y)
            step = 1.0 / (theta * L)
            z = prox(z_old, g_y, step)[0]
            x = (1.0 - theta) * x_old + theta * z

            if beta >= 1.0:
                break

            xy = x - y
            xy_sq = float(xy @ xy)
            if xy_sq == 0.0:
                break

            f_x, g_x = smooth_counted(x)
            if backtrack_simple:
                q_x = f_y + float(xy @ g_y) + 0.5 * L * xy_sq
                local_l = L + 2.0 * max(f_x - q_x, 0.0) / xy_sq
                backtrack_simple = (
                    abs(f_y - f_x)
                    >= backtrack_tol * max(abs(f_x), abs(f_y)))
            else:
                local_l = 2.0 * float(xy @ (g_x - g_y)) / xy_sq

            if local_l <= L or L >= l_exact:
                break

            n_backtracks += 1
            if not math.isinf(local_l):
                L = min(l_exact, local_l)
            else:
                local_l = L
            L = min(l_exact, max(local_l, L / beta))

        # Loss history at x (TFOCS-validation mode, reference :302-307):
        # a third full pass in the reference; the oracle mirrors it.
        f_x_hist, g_x_hist = smooth_counted(x)
        c_x = prox(x, g_x_hist, 0.0)[1]
        loss_history.append(f_x_hist + c_x)

        if math.isnan(f_y) or math.isinf(f_y):
            aborted = True
            break

        norm_x = float(np.linalg.norm(x))
        norm_dx = float(np.linalg.norm(x - x_old))
        if norm_dx == 0.0 and n_iter > 1:
            break
        if norm_dx < convergence_tol * max(norm_x, 1.0):
            break

        if may_restart and float(g_y @ (x - x_old)) > 0.0:
            z = x
            theta = math.inf
            backtrack_simple = True
            n_restarts += 1

    return OracleResult(
        weights=x,
        loss_history=loss_history,
        num_smooth_calls=calls["n"],
        num_backtracks=n_backtracks,
        num_restarts=n_restarts,
        aborted_non_finite=aborted,
    )
