"""Host-orchestrated L-BFGS: the streaming / cross-process twin of
``core.lbfgs``.

Same decision algebra as the fused loops (same Wolfe / orthant-wise
conditions, same curvature safeguard, same convergence test — see
``core/lbfgs.py`` for the MLlib/Breeze pinning), but with the outer
loop and line search in Python and only the math on device, mirroring
``core.host_agd``'s split: a *streamed* objective
(``data.streaming.make_streaming_smooth`` + the updater's smooth
penalty) contains a host loop and cannot live inside
``lax.while_loop``; a cross-process global-array objective cannot be
closed over by a fused jit.  Control scalars sync to the host once per
objective evaluation — for macro-batch workloads the stream dominates.
Both quasi-Newton drivers have host twins: :func:`run_lbfgs_host`
(smooth penalties, strong Wolfe) and :func:`run_owlqn_host` (L1 /
elastic net, orthant-wise).

Parity with the fused loop is pinned by
``tests/test_lbfgs.py::TestHostTwin`` (identical iteration counts and
trajectories on in-memory problems).  Scope of that exactness: the host
driver compares control scalars as Python float64, the fused loop in
the objective's dtype — under x64 (the test suite) every branch is
bit-identical; with an f32 objective a decision sitting exactly on a
Wolfe/convergence boundary can round differently, so f32 parity is
trajectory-level, not branch-level (the multihost smoke asserts
matching stop modes and objective values, not counts).
"""
# graftlint: disable-file=host-sync -- host-orchestrated driver by
# design: streamed / cross-process objectives cannot live inside
# lax.while_loop, so Wolfe control scalars sync per evaluation

from __future__ import annotations

from typing import Any, Callable, List, NamedTuple

import numpy as np

from . import tvec
from .lbfgs import (LBFGSConfig, LS_STOP_ARMIJO, LS_STOP_NOISE_FLOOR,
                    LS_STOP_NONE)


def _carry_eps(w0) -> float:
    """The host mirror of the fused driver's carry-dtype resolution
    (``lbfgs._carry_dtype``) — the noise-floor classification
    threshold's machine epsilon."""
    import jax

    dt = np.promote_types(np.result_type(
        *[np.asarray(l).dtype
          for l in jax.tree_util.tree_leaves(w0)]), np.float32)
    return float(np.finfo(dt).eps)


class HostLBFGSResult(NamedTuple):
    weights: Any
    # (num_iters + 1,): entry 0 is f at this SEGMENT's start (f(w0)
    # cold, warm.f resumed), then one entry per accepted step — so
    # chained segments join with seg2.loss_history[1:]
    loss_history: np.ndarray
    num_iters: int  # iterations executed in THIS segment
    converged: bool
    ls_failed: bool
    aborted_non_finite: bool
    grad_norm: float
    num_fn_evals: int
    # the exact continuation carry (gradient + curvature pairs) — feed
    # back as ``warm`` to continue precisely where this run stopped
    final_g: Any = None
    final_pairs: tuple = ()
    # the SMOOTH part's value at exit: for the smooth driver this
    # equals loss_history[-1]; for OWL-QN the history holds F = f + L1
    # while the warm carry needs f — from_result uses this when set
    final_f_smooth: Any = None
    # WHY the line search stopped the run (``lbfgs.LS_STOP_*`` codes;
    # 0/none when ``ls_failed`` is False) — the host mirror of the
    # fused result's ``ls_stop_reason``
    ls_stop_reason: int = 0


class HostLBFGSWarm(NamedTuple):
    """Complete inter-iteration carry: weights, objective value,
    gradient, the m curvature pairs (oldest first), and iterations
    already executed — enough that a resumed run makes decisions
    IDENTICAL to an uninterrupted one (unlike restarting from bare
    weights, which forgets the Hessian approximation and degrades to a
    gamma=1 first step)."""

    w: Any
    f: float
    g: Any
    pairs: tuple  # ((s, y, rho), ...) oldest first, len <= m
    prior_iters: int

    @classmethod
    def from_result(cls, res: "HostLBFGSResult",
                    prior_iters: int = 0) -> "HostLBFGSWarm":
        """The carry out of a finished segment; ``prior_iters`` is the
        iteration total BEFORE that segment (chain it forward)."""
        f = (res.final_f_smooth if res.final_f_smooth is not None
             else res.loss_history[-1])
        return cls(w=res.weights, f=float(f),
                   g=res.final_g, pairs=tuple(res.final_pairs),
                   prior_iters=prior_iters + res.num_iters)


def _pin_grad(g, w):
    """Cast a gradient's leaves to the weight leaves' dtypes — the host
    mirror of the fused drivers' ``lbfgs._pin_objective`` convention
    (ONE copy; all evaluation sites use it)."""
    return tvec.tmap(lambda gi, wi: gi.astype(np.asarray(wi).dtype),
                     g, w)


def _wolfe_gen(w, f0, g0, d, cfg: LBFGSConfig):
    """Strong-Wolfe search as a GENERATOR — the eager mirror of
    ``lbfgs._wolfe_search`` (same bracket/zoom decisions, same
    budgets), with every objective evaluation expressed as
    ``f, g = yield w_trial``.  The solo driver feeds it directly; the
    multi-lane scheduler batches many lanes' pending yields into one
    multi-evaluation — ONE copy of the decision algebra either way.
    Returns ``(t, f_t, g_t, evals, ok, fail_info)`` via StopIteration;
    ``fail_info = (fail_phase, f_best, t_last, dg0)`` mirrors the fused
    ``_wolfe_search`` and feeds the ``ls_stop_reason`` split."""
    dg0 = float(tvec.dot(g0, d))
    evals = 0

    def _eval(t):
        # one copy of evaluate-and-pin (the old eval_at closure)
        f, g = yield tvec.axpby(1.0, w, t, d)
        return float(f), _pin_grad(g, w)

    t = 1.0
    f_t, g_t = yield from _eval(t)
    evals += 1
    dg_t = float(tvec.dot(g_t, d))
    t_lo, f_lo = 0.0, f0
    t_hi, f_hi = 0.0, f0
    stage = 0  # 0 bracket, 1 zoom
    it = 0
    while True:
        armijo = f_t <= f0 + cfg.c1 * t * dg0
        curv = abs(dg_t) <= -cfg.c2 * dg0
        if armijo and curv:
            return t, f_t, g_t, evals, True, (0, f_lo, t, dg0)
        if stage == 0:
            rise = (not armijo) or (it > 0 and f_t >= f_lo)
            if rise:
                t_lo, f_lo, t_hi, f_hi = t_lo, f_lo, t, f_t
                stage, it = 1, 0
            elif dg_t >= 0:
                t_lo, f_lo, t_hi, f_hi = t, f_t, t_lo, f_lo
                stage, it = 1, 0
            else:
                t_lo, f_lo = t, f_t
                it += 1
                if it >= cfg.max_ls_steps:
                    return 0.0, f0, g0, evals, False, (1, f_lo, t, dg0)
                t = t * cfg.max_step_growth
                f_t, g_t = yield from _eval(t)
                evals += 1
                dg_t = float(tvec.dot(g_t, d))
                continue
        else:
            z_rise = (not armijo) or (f_t >= f_lo)
            if z_rise:
                t_hi, f_hi = t, f_t
            else:
                if dg_t * (t_hi - t_lo) >= 0:
                    t_hi, f_hi = t_lo, f_lo
                t_lo, f_lo = t, f_t
            it += 1
            if it >= cfg.max_ls_steps:
                return 0.0, f0, g0, evals, False, (2, f_lo, t, dg0)
        t = 0.5 * (t_lo + t_hi)
        f_t, g_t = yield from _eval(t)
        evals += 1
        dg_t = float(tvec.dot(g_t, d))


def _two_loop_host(q0, pairs):
    """The host two-loop recursion over ``pairs`` (oldest first) — ONE
    copy shared by both host drivers, same op order as the fused
    ``lbfgs._two_loop``."""
    q = q0
    alphas = []
    for s, y, rho in reversed(pairs):  # newest -> oldest
        a = float(rho * tvec.dot(s, q))
        q = tvec.axpby(1.0, q, -a, y)
        alphas.append(a)
    if pairs:
        s_n, y_n, _ = pairs[-1]
        yy = float(tvec.dot(y_n, y_n))
        gamma = float(tvec.dot(s_n, y_n)) / max(
            yy, np.finfo(np.float64).tiny)
    else:
        gamma = 1.0
    r = tvec.scale(gamma, q)
    for (s, y, rho), a in zip(pairs, reversed(alphas)):
        b = float(rho * tvec.dot(y, r))
        r = tvec.axpby(1.0, r, a - b, s)
    return r


def run_lbfgs_host(
    objective: Callable,
    w0: Any,
    config: LBFGSConfig = LBFGSConfig(),
    *,
    warm: HostLBFGSWarm | None = None,
    on_iteration: Callable | None = None,
) -> HostLBFGSResult:
    """Minimize a HOST-callable ``objective(w) -> (f, g)`` — e.g. a
    streamed smooth plus penalty, or an eager cross-process shard_map
    smooth.

    ``warm`` (a :class:`HostLBFGSWarm`, e.g.
    ``HostLBFGSWarm.from_result(prev)``) continues a prior segment
    EXACTLY: gradient and curvature pairs carry over, no objective
    re-evaluation at the start, and ``prior_iters`` counts against
    ``num_iterations`` — a kill/resume chain reproduces the
    uninterrupted run (``tests/test_lbfgs.py::TestHostTwin``).
    ``on_iteration(state_dict)`` fires after every accepted step with
    the full carry ``{w, f, g, pairs, it}`` (``it`` is the TOTAL
    iteration count including any warm prior) — checkpoint from it with
    ``HostLBFGSWarm(w=s["w"], f=s["f"], g=s["g"], pairs=s["pairs"],
    prior_iters=s["it"])``."""
    gen = _lbfgs_gen(w0, config, warm=warm, on_iteration=on_iteration)
    try:
        wq = next(gen)
        while True:
            wq = gen.send(objective(wq))
    except StopIteration as e:
        return e.value


def _lbfgs_gen(w0, config: LBFGSConfig, *, warm=None,
               on_iteration=None):
    """The host L-BFGS algorithm as a generator (``f, g = yield w`` per
    evaluation) — the ONE copy both :func:`run_lbfgs_host` (solo
    driver) and :func:`run_lbfgs_host_multi` (lock-step lane scheduler)
    execute, so per-lane decisions cannot drift from solo runs."""
    cfg = config
    m = int(cfg.num_corrections)
    if m < 1:
        raise ValueError("num_corrections must be >= 1")

    if warm is not None:
        w, f, g = warm.w, float(warm.f), warm.g
        pairs: List[tuple] = list(warm.pairs)[-m:]
        it = int(warm.prior_iters)
        evals = 0
    else:
        f, g = yield w0
        f = float(f)
        w = w0
        g = _pin_grad(g, w)
        pairs = []
        it = 0
        evals = 1
    hist: List[float] = [f]
    converged = ls_failed = aborted = False
    ls_reason = LS_STOP_NONE
    eps = _carry_eps(w0)
    if not np.isfinite(f):
        aborted = True

    while not (converged or ls_failed or aborted) and \
            it < cfg.num_iterations:
        d = tvec.scale(-1.0, _two_loop_host(g, pairs))
        if not float(tvec.dot(g, d)) < 0:  # stale curvature fallback
            d = tvec.scale(-1.0, g)

        t, f_n, g_n, ev, ok, ls_info = yield from _wolfe_gen(
            w, f, g, d, cfg)
        evals += ev
        if not ok:
            ls_failed = True
            # same classification as the fused driver (lbfgs.LS_STOP_*
            # docs): noise floor iff no trial improved f beyond the
            # carry dtype's resolution AND the last trial's first-order
            # expected decrease was below it too
            fail_phase, f_best, t_last, dg0 = ls_info
            tol_f = 32 * eps * max(abs(f), 1.0)
            if (f - f_best) <= tol_f and abs(dg0 * t_last) <= tol_f:
                ls_reason = LS_STOP_NOISE_FLOOR
            else:
                ls_reason = int(fail_phase)
            break
        if not np.isfinite(f_n):
            aborted = True
            break
        w_n = tvec.axpby(1.0, w, t, d)
        s = tvec.sub(w_n, w)
        y = tvec.sub(g_n, g)
        sy = float(tvec.dot(s, y))
        if sy > 1e-10 * float(tvec.norm(s)) * float(tvec.norm(y)):
            pairs.append((s, y, 1.0 / sy))
            if len(pairs) > m:
                pairs.pop(0)
        improv = (f - f_n) / max(abs(f), abs(f_n), 1.0)
        if improv <= cfg.convergence_tol:
            converged = True
        if cfg.grad_tol > 0 and float(tvec.norm(g_n)) < cfg.grad_tol:
            converged = True
        w, f, g = w_n, f_n, g_n
        it += 1
        hist.append(f)
        if on_iteration is not None:
            on_iteration({"w": w, "f": f, "g": g,
                          "pairs": tuple(pairs), "it": it})

    seg_iters = it - (int(warm.prior_iters) if warm is not None else 0)
    return HostLBFGSResult(
        weights=w, loss_history=np.asarray(hist), num_iters=seg_iters,
        converged=converged, ls_failed=ls_failed,
        aborted_non_finite=aborted, grad_norm=float(tvec.norm(g)),
        num_fn_evals=evals, final_g=g, final_pairs=tuple(pairs),
        final_f_smooth=f, ls_stop_reason=ls_reason)


def run_owlqn_host(
    objective_smooth: Callable,
    w0: Any,
    l1_reg: float,
    config: LBFGSConfig = LBFGSConfig(),
    *,
    warm: HostLBFGSWarm | None = None,
    on_iteration: Callable | None = None,
) -> HostLBFGSResult:
    """Host-loop OWL-QN — the streamed / cross-process twin of
    ``core.lbfgs.run_owlqn``, mirroring its decision algebra the way
    :func:`run_lbfgs_host` mirrors the smooth driver.  ``warm.f``
    carries the SMOOTH part's value (the L1 term is recomputed from the
    weights); ``loss_history`` entries are the full objective F.
    """
    import jax.numpy as jnp

    cfg = config
    m = int(cfg.num_corrections)
    if m < 1:
        raise ValueError("num_corrections must be >= 1")
    if l1_reg < 0:
        raise ValueError("l1_reg must be >= 0")
    l1 = float(l1_reg)

    from .lbfgs import _pseudo_gradient

    def pseudo_grad(w, g):
        return _pseudo_gradient(w, g, l1)

    if warm is not None:
        w, f, g = warm.w, float(warm.f), warm.g
        pairs: List[tuple] = list(warm.pairs)[-m:]
        it = int(warm.prior_iters)
        evals = 0
    else:
        f, g = objective_smooth(w0)
        f = float(f)
        w = w0
        g = _pin_grad(g, w)
        pairs = []
        it = 0
        evals = 1
    big_f = f + l1 * float(tvec.l1_norm(w))
    hist: List[float] = [big_f]
    converged = ls_failed = aborted = False
    ls_reason = LS_STOP_NONE
    eps = _carry_eps(w0)
    if not np.isfinite(big_f):
        aborted = True

    while not (converged or ls_failed or aborted) and \
            it < cfg.num_iterations:
        pg = pseudo_grad(w, g)
        d = tvec.scale(-1.0, _two_loop_host(pg, pairs))
        d = tvec.tmap(lambda di, pgi: jnp.where(di * pgi < 0, di, 0.0),
                      d, pg)
        if float(tvec.dot(d, d)) == 0:
            d = tvec.scale(-1.0, pg)
        xi = tvec.tmap(
            lambda wi, pgi: jnp.where(wi != 0, jnp.sign(wi),
                                      jnp.sign(-pgi)), w, pg)

        def trial(t):
            nonlocal evals
            w_t = tvec.tmap(
                lambda wi, di, xii: jnp.where(
                    (wi + t * di) * xii > 0, wi + t * di, 0.0),
                w, d, xi)
            f_t, g_t = objective_smooth(w_t)
            g_t = _pin_grad(g_t, w)
            evals += 1
            return (w_t, float(f_t),
                    float(f_t) + l1 * float(tvec.l1_norm(w_t)), g_t)

        t, k, ok = 1.0, 0, False
        while True:
            w_n, f_n, big_f_n, g_n = trial(t)
            gain = float(tvec.dot(pg, tvec.sub(w_n, w)))
            ok = (big_f_n <= big_f + cfg.c1 * gain
                  and np.isfinite(big_f_n))
            k += 1
            if ok or k >= cfg.max_ls_steps:
                break
            t *= 0.5
        if not ok:
            ls_failed = True
            # mirror the fused driver's flags: a budget exhausted ON a
            # non-finite trial also marks the abort
            aborted = not np.isfinite(big_f_n)
            # same classification as the fused OWL-QN (lbfgs.LS_STOP_*)
            tol_f = 32 * eps * max(abs(big_f), 1.0)
            if np.isfinite(big_f_n) and \
                    abs(big_f_n - big_f) <= tol_f and \
                    abs(gain) <= tol_f:
                ls_reason = LS_STOP_NOISE_FLOOR
            else:
                ls_reason = LS_STOP_ARMIJO
            break
        s = tvec.sub(w_n, w)
        y = tvec.sub(g_n, g)
        sy = float(tvec.dot(s, y))
        if sy > 1e-10 * float(tvec.norm(s)) * float(tvec.norm(y)):
            pairs.append((s, y, 1.0 / sy))
            if len(pairs) > m:
                pairs.pop(0)
        improv = (big_f - big_f_n) / max(abs(big_f), abs(big_f_n), 1.0)
        if improv <= cfg.convergence_tol:
            converged = True
        if cfg.grad_tol > 0 and float(
                tvec.norm(pseudo_grad(w_n, g_n))) < cfg.grad_tol:
            converged = True
        w, f, g, big_f = w_n, f_n, g_n, big_f_n
        it += 1
        hist.append(big_f)
        if on_iteration is not None:
            on_iteration({"w": w, "f": f, "g": g,
                          "pairs": tuple(pairs), "it": it})

    seg_iters = it - (int(warm.prior_iters) if warm is not None else 0)
    return HostLBFGSResult(
        weights=w, loss_history=np.asarray(hist), num_iters=seg_iters,
        converged=converged, ls_failed=ls_failed,
        aborted_non_finite=aborted,
        grad_norm=float(tvec.norm(pseudo_grad(w, g))),
        num_fn_evals=evals, final_g=g, final_pairs=tuple(pairs),
        final_f_smooth=f, ls_stop_reason=ls_reason)


class HostLBFGSMultiResult(NamedTuple):
    """Per-lane fields stacked on a leading K axis; ``loss_history`` is
    ``(K, max_iters + 1)`` NaN-padded per lane (lane k's live prefix is
    ``[:num_iters[k] + 1]``).  ``eval_rounds`` counts the multi-
    evaluations (stream passes) the lock-step schedule consumed — the
    savings claim vs ``sum(num_fn_evals)`` sequential passes."""

    weights: Any
    loss_history: np.ndarray
    num_iters: np.ndarray
    converged: np.ndarray
    ls_failed: np.ndarray
    aborted_non_finite: np.ndarray
    grad_norm: np.ndarray
    num_fn_evals: np.ndarray
    eval_rounds: int
    ls_stop_reason: np.ndarray = None  # (K,) lbfgs.LS_STOP_* codes


def run_lbfgs_host_multi(
    objective_multi: Callable,
    w0_stacked: Any,
    config: LBFGSConfig = LBFGSConfig(),
) -> HostLBFGSMultiResult:
    """K lock-step L-BFGS lanes over ONE multi-evaluation per round —
    the quasi-Newton twin of ``host_agd.run_agd_host_multi``.

    ``objective_multi(W_stacked) -> ((K,) values, stacked grads)`` —
    e.g. ``data.streaming.make_streaming_eval_multi`` plus per-lane
    penalties: K regularization strengths then share one stream read
    per evaluation round instead of re-streaming per lane.

    Each lane executes the EXACT solo algorithm (:func:`_lbfgs_gen` —
    the same generator ``run_lbfgs_host`` drives), so the scheduler
    cannot change any lane's decision logic; per-lane results match
    solo runs to the multi-evaluation kernel's own rounding (a vmapped
    kernel may fuse reductions ~1 ulp differently than the solo one —
    pinned in ``tests/test_lbfgs.py::TestStreamedMultiLane``).  A lane
    that finishes early contributes its final weights to later rounds
    (the multi-evaluation needs a full stack) and its result is frozen.
    """
    import jax

    leaves = jax.tree_util.tree_leaves(w0_stacked)
    if not leaves:
        raise ValueError("w0_stacked must have at least one leaf")
    k_lanes = leaves[0].shape[0]
    lane = lambda tree, k: jax.tree_util.tree_map(
        lambda l: l[k], tree)

    gens = []
    queries: List[Any] = []
    results: List[Any] = [None] * k_lanes
    for k in range(k_lanes):
        g = _lbfgs_gen(lane(w0_stacked, k), config)
        gens.append(g)
        queries.append(next(g))  # a fresh gen always yields w0 first

    import jax.numpy as jnp

    rounds = 0
    while any(r is None for r in results):
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls),
            *[queries[k] if results[k] is None else results[k].weights
              for k in range(k_lanes)])
        fs, Gs = objective_multi(stacked)
        rounds += 1
        fs = np.asarray(fs)
        for k in range(k_lanes):
            if results[k] is not None:
                continue
            try:
                queries[k] = gens[k].send((fs[k], lane(Gs, k)))
            except StopIteration as e:
                results[k] = e.value

    max_it = max(r.num_iters for r in results)
    hist = np.full((k_lanes, max_it + 1), np.nan)
    for k, r in enumerate(results):
        hist[k, :r.num_iters + 1] = r.loss_history
    return HostLBFGSMultiResult(
        weights=jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *[r.weights for r in results]),
        loss_history=hist,
        num_iters=np.asarray([r.num_iters for r in results]),
        converged=np.asarray([r.converged for r in results]),
        ls_failed=np.asarray([r.ls_failed for r in results]),
        aborted_non_finite=np.asarray(
            [r.aborted_non_finite for r in results]),
        grad_norm=np.asarray([r.grad_norm for r in results]),
        num_fn_evals=np.asarray([r.num_fn_evals for r in results]),
        eval_rounds=rounds,
        ls_stop_reason=np.asarray(
            [r.ls_stop_reason for r in results]))
