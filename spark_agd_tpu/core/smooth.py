"""Builders turning (Gradient, data) into the ``smooth(w) -> (f, g)`` the
optimizer core consumes.

This is the single-device analogue of the reference's ``applySmooth``
(reference ``AcceleratedGradientDescent.scala:192-208``): mean loss and mean
gradient over the full dataset.  No broadcast, no tree-reduce — the data is
already device-resident and XLA fuses the mean into the kernels.  The mesh-
sharded builders live in ``parallel/`` and have the same signature, so the
core never knows whether its reduction crossed a chip boundary.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp

from . import tvec
from ..ops.losses import Gradient
from ..ops.prox import Prox


def make_smooth_staged(gradient: Gradient, X, y, mask=None):
    """``(build, data_args)``: the program/data split for jit callers.

    ``gradient.prepare`` runs ONCE here, at data-placement time, and the
    prepared arrays come back as ``data_args`` — a pytree the caller
    passes THROUGH ``jax.jit`` as runtime arguments.  ``build(*traced)``
    is then called inside the traced step and returns the
    ``(smooth, smooth_loss)`` closures over *tracers*.

    Why the split matters: closing a jitted step over the concrete data
    arrays embeds them as jaxpr constants, and XLA's constant handling
    makes compile time scale with nnz — measured 11.5 s at 2.6M nnz /
    43 s at 10.3M nnz for the same program that compiles in ~2 s with
    the data passed as arguments (the r4 scale-1.0 rcv1 row hit
    ``compile_s: 1842.74``).  The reference never meets this failure
    mode (its data stays in RDD partitions, outside any compiled
    program, reference ``AcceleratedGradientDescent.scala:192-208``);
    the TPU-native analogue is: data rides as device-resident jit
    ARGUMENTS, never as program constants.
    """
    X, y, mask = gradient.prepare(X, y, mask)

    def build(Xa, ya, ma):
        def smooth(w):
            return gradient.mean_loss_and_grad(w, Xa, ya, ma)

        def smooth_loss(w):
            loss_sum, _, n = gradient.batch_loss_and_grad(w, Xa, ya, ma)
            return loss_sum / jnp.asarray(n, loss_sum.dtype)

        return smooth, smooth_loss

    return build, (X, y, mask)


def make_smooth(gradient: Gradient, X, y, mask=None) -> Callable:
    """``smooth(w) -> (mean_loss, mean_grad)`` over one in-memory batch,
    closed over the concrete prepared arrays (eager / direct use).
    Inside a ``jax.jit`` program prefer :func:`make_smooth_staged` —
    see its docstring for the compile-time reason."""
    build, args = make_smooth_staged(gradient, X, y, mask)
    return build(*args)[0]


def make_smooth_loss(gradient: Gradient, X, y, mask=None) -> Callable:
    """Loss-only evaluation (no gradient) — used by ``loss_mode='x'`` when
    backtracking is off.  Falls back to the full kernel; specialised
    loss-only kernels can override later."""
    build, args = make_smooth_staged(gradient, X, y, mask)
    return build(*args)[1]


def make_prox(p: Prox, reg_param: float):
    """Close a ``Prox`` over its regularization parameter: the pair
    ``(prox(w, g, step), reg_value(w))`` the core consumes (the reference
    threads ``regParam`` through every ``Updater.compute`` call instead,
    reference ``:215-220``)."""

    def prox(w, g, step):
        return p.prox(w, g, step, reg_param)

    def reg_value(w):
        return p.reg_value(w, reg_param)

    return prox, reg_value
