"""Mini-batch gradient descent with spark-mllib 1.3.0 semantics.

The reference's entire test strategy is equivalence against MLlib's
``GradientDescent.runMiniBatchSGD`` (reference Suite:78-86, :118-126,
:225-233 — SURVEY §4 calls it the correctness oracle).  To reproduce that
strategy the framework must carry its own GD comparator with the *same*
semantics, faithfully including the parts the AGD path deliberately
bypasses:

- the hidden per-iteration step rescaling ``stepSize / sqrt(iter)`` that
  MLlib updaters apply (the reference defeats it with ``iter = 1`` at
  ``AcceleratedGradientDescent.scala:218-219``; GD *keeps* it);
- loss-history entry i = smooth loss at the pre-update weights plus the
  regularization value carried over from the *previous* update (seeded by a
  ``step = 0`` updater call before the loop);
- Bernoulli mini-batch sampling per iteration (``miniBatchFraction``),
  dividing by the realised batch size, skipping the update only when the
  sample is empty;
- no convergence test — GD always runs all ``num_iterations`` (Spark 1.3
  behavior; convergence-tol arrived in Spark 1.5).

Compiled as one ``lax.fori_loop`` program; sampling uses a per-iteration
Bernoulli mask folded into the kernels' mask argument, so shapes stay
static (the TPU answer to ``RDD.sample``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import tvec
from ..ops.losses import Gradient
from ..ops.prox import Prox


class GDResult(NamedTuple):
    weights: Any
    loss_history: jax.Array  # (num_iterations,)


def run_minibatch_sgd(
    gradient: Gradient,
    updater: Prox,
    X,
    y,
    initial_weights,
    *,
    step_size: float = 1.0,
    num_iterations: int = 100,
    reg_param: float = 0.0,
    minibatch_fraction: float = 1.0,
    mask=None,
    seed: int = 42,
    data_axis: Optional[str] = None,
    global_rows: Optional[int] = None,
    row_offset=None,
) -> GDResult:
    """Trace-compatible MLlib-1.3 ``runMiniBatchSGD``.  ``mask`` is the
    data-layer padding mask; sampling masks compose with it.

    Mesh composition (the reference's GD *is* distributed — MLlib's
    ``runMiniBatchSGD`` runs the same treeAggregate as AGD): call inside
    a ``shard_map`` body with LOCAL ``(X, y, mask)`` shards and
    ``data_axis`` set — the (Σloss, Σgrad, n) sums psum over the axis
    before every division.  Sampling stays globally consistent: each
    shard draws the SAME full-length Bernoulli vector (``global_rows``)
    and slices its contiguous block at ``row_offset``, so an N-way mesh
    run takes bit-identical sample sequences to a single-device run on
    the identically-padded arrays.  ``api.run_minibatch_sgd(mesh=...)``
    wraps this.
    """
    full_batch = minibatch_fraction >= 1.0
    base_key = jax.random.PRNGKey(seed)
    w0 = initial_weights
    dt = jnp.promote_types(
        jnp.result_type(*jax.tree_util.tree_leaves(w0)), jnp.float32)

    # Seed regVal exactly as MLlib does: an updater call with step 0 at the
    # initial weights (the same step=0 identity the reference leans on).
    reg_val0 = jnp.asarray(
        updater.prox(w0, tvec.zeros_like(w0), 0.0, reg_param)[1], dt)

    n_rows = X.shape[0]
    g_rows = n_rows if global_rows is None else int(global_rows)

    def body(i, carry):
        w, reg_val, hist = carry
        it = i + 1  # MLlib iterations are 1-based

        if full_batch:
            it_mask = mask
        else:
            key = jax.random.fold_in(base_key, it)
            sample = jax.random.bernoulli(
                key, minibatch_fraction, (g_rows,)).astype(dt)
            if global_rows is not None:
                sample = lax.dynamic_slice(sample, (row_offset,),
                                           (n_rows,))
            it_mask = sample if mask is None else sample * jnp.asarray(
                mask, dt)

        loss_sum, grad_sum, n = gradient.batch_loss_and_grad(
            w, X, y, it_mask)
        if data_axis is not None:
            # the whole treeAggregate comb tree, one ICI all-reduce —
            # identical on every device, so the driver math below stays
            # coherent across the mesh
            loss_sum = lax.psum(loss_sum, data_axis)
            grad_sum = tvec.tmap(lambda g: lax.psum(g, data_axis),
                                 grad_sum)
            n = lax.psum(n, data_axis)
        nf = jnp.asarray(n, dt)
        nonempty = nf > 0

        loss = jnp.where(nonempty, loss_sum / jnp.maximum(nf, 1.0), 0.0)
        hist = hist.at[i].set(jnp.where(nonempty, loss + reg_val,
                                        jnp.asarray(jnp.nan, dt)))

        # MLlib's hidden rescaling, applied by the driver here because our
        # prox operators are rescaling-free by design.
        this_step = step_size / jnp.sqrt(jnp.asarray(it, dt))
        g_mean = tvec.scale(1.0 / jnp.maximum(nf, 1.0), grad_sum)
        w_new, reg_new = updater.prox(w, g_mean, this_step, reg_param)
        # empty sample: skip the update entirely (MLlib logs and continues)
        w = tvec.tmap(lambda a, b: jnp.where(nonempty, b, a), w, w_new)
        reg_val = jnp.where(nonempty, reg_new, reg_val)
        return w, reg_val, hist

    hist0 = jnp.zeros((num_iterations,), dt)
    w, _, hist = lax.fori_loop(0, num_iterations, body,
                               (w0, reg_val0, hist0))
    return GDResult(weights=w, loss_history=hist)
