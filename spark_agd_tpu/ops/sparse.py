"""CSR sparse matrix for TPU kernels (SURVEY §7 hard part 3).

rcv1.binary (~47k features) and url_combined (~3.2M features) are far too
sparse to densify at full scale.  The MXU cannot consume CSR directly, so
the sparse path lowers to gather + ``segment_sum`` (matvec) and a scatter-
add (rmatvec) — XLA compiles both to decent TPU code, and the row-id
layout (COO-style, not indptr) is exactly what ``segment_sum`` wants and
what shards cleanly by nnz ranges later.

``CSRMatrix`` is a pytree (arrays are leaves, shape is static aux data) so
it can close over jit/shard_map boundaries and ride inside the fused AGD
loop like any dense operand.  The loss kernels dispatch on it through
``ops.losses.matvec``/``rmatvec`` — the same ``Gradient`` classes serve
dense and sparse data.

Padding contract: ``nnz`` may include padding entries (value 0.0 pointing
at row 0 / col 0) so nnz-sharded layouts can be rectangular; zero values
contribute nothing to either product.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class CSRMatrix:
    """Row-sparse matrix in COO-with-row-ids form.

    ``row_ids``/``col_ids``/``values`` are (nnz,) arrays; ``shape`` is
    static.  Build from scipy-style CSR via ``from_csr_arrays``.
    """

    def __init__(self, row_ids, col_ids, values, shape: Tuple[int, int]):
        self.row_ids = row_ids
        self.col_ids = col_ids
        self.values = values
        self.shape = tuple(shape)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.row_ids, self.col_ids, self.values), self.shape

    @classmethod
    def tree_unflatten(cls, shape, leaves):
        return cls(*leaves, shape=shape)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_csr_arrays(cls, indptr, indices, values,
                        n_features: int) -> "CSRMatrix":
        indptr = np.asarray(indptr)
        n_rows = len(indptr) - 1
        counts = np.diff(indptr)
        row_ids = np.repeat(np.arange(n_rows, dtype=np.int32), counts)
        return cls(jnp.asarray(row_ids), jnp.asarray(indices, jnp.int32),
                   jnp.asarray(values), (n_rows, int(n_features)))

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    # -- products ----------------------------------------------------------
    def matvec(self, w):
        """``X @ w`` -> (n_rows,): gather + segment-sum over rows."""
        prods = self.values * jnp.take(w, self.col_ids, axis=0)
        return jax.ops.segment_sum(prods, self.row_ids,
                                   num_segments=self.shape[0])

    def rmatvec(self, v):
        """``X.T @ v`` -> (n_features,): scatter-add into columns.  Output
        dtype follows promotion rules, matching the dense ``X.T @ v``."""
        contrib = self.values * jnp.take(v, self.row_ids, axis=0)
        out_dt = jnp.result_type(self.values, v)
        return jnp.zeros(self.shape[1], out_dt).at[self.col_ids].add(contrib)

    def matmat(self, W):
        """``X @ W`` for (D, K) dense W -> (n_rows, K)."""
        prods = self.values[:, None] * jnp.take(W, self.col_ids, axis=0)
        return jax.ops.segment_sum(prods, self.row_ids,
                                   num_segments=self.shape[0])

    def rmatmat(self, V):
        """``X.T @ V`` for (n_rows, K) dense V -> (n_features, K)."""
        contrib = self.values[:, None] * jnp.take(V, self.row_ids, axis=0)
        out_dt = jnp.result_type(self.values, V)
        return jnp.zeros((self.shape[1], V.shape[1]),
                         out_dt).at[self.col_ids].add(contrib)


@jax.tree_util.register_pytree_node_class
class RowShardedCSR:
    """A CSR batch laid out for the mesh ``data`` axis (sparse DP).

    The reference's distributed pass works on any RDD of sparse vectors
    (``Gradient.compute`` takes a ``Vector``, reference
    ``AcceleratedGradientDescent.scala:196-204``); this is the TPU layout
    that restores that capability for mesh parallelism.  Rows are assigned
    to shards (nnz-balanced by default — see ``parallel.mesh.
    shard_csr_batch``), each shard's entries are re-indexed to LOCAL row
    ids and padded to a common ``nnz_per_shard`` so the stacked arrays are
    rectangular; inside ``shard_map`` every device reconstructs its slice
    as an ordinary :class:`CSRMatrix` of shape ``(rows_per_shard, D)`` —
    one sparse kernel implementation serves every layout.

    ``row_ids``/``col_ids``/``values`` are ``(n_shards * nnz_per_shard,)``
    device arrays sharded over the data axis; padding entries are value
    0.0 at local row 0 / col 0 (inert in both products, see the module
    padding contract).  ``shape`` is the GLOBAL logical shape (unpadded
    row count); per-shard row slots beyond the real rows carry mask 0 in
    the accompanying ``ShardedBatch.mask``.
    """

    def __init__(self, row_ids, col_ids, values, shape: Tuple[int, int],
                 rows_per_shard: int, n_shards: int):
        self.row_ids = row_ids
        self.col_ids = col_ids
        self.values = values
        self.shape = tuple(shape)
        self.rows_per_shard = int(rows_per_shard)
        self.n_shards = int(n_shards)

    def tree_flatten(self):
        return ((self.row_ids, self.col_ids, self.values),
                (self.shape, self.rows_per_shard, self.n_shards))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, rows_per_shard, n_shards = aux
        return cls(*leaves, shape=shape, rows_per_shard=rows_per_shard,
                   n_shards=n_shards)

    @property
    def sharding(self):
        """The values array's sharding (all three leaves are placed
        identically) — lets ``api.run`` recover the mesh the same way it
        does from a dense ``ShardedBatch.X``."""
        return self.values.sharding

    @property
    def nnz_per_shard(self) -> int:
        return int(self.values.shape[0]) // self.n_shards

    def local_csr(self, row_ids, col_ids, values) -> CSRMatrix:
        """Reassemble ONE shard's slice (as seen inside ``shard_map``)
        into a local CSRMatrix of shape ``(rows_per_shard, D)``."""
        return CSRMatrix(row_ids, col_ids, values,
                         (self.rows_per_shard, self.shape[1]))


def matvec(X, w):
    """Polymorphic ``X @ w`` (dense array or CSRMatrix) used by the loss
    kernels; 2-D ``w`` routes to matmat."""
    if isinstance(X, CSRMatrix):
        return X.matmat(w) if w.ndim == 2 else X.matvec(w)
    return X @ w


def rmatvec(X, v):
    """Polymorphic ``X.T @ v``."""
    if isinstance(X, CSRMatrix):
        return X.rmatmat(v) if v.ndim == 2 else X.rmatvec(v)
    return X.T @ v

