"""CSR sparse matrix for TPU kernels (SURVEY §7 hard part 3).

rcv1.binary (~47k features) and url_combined (~3.2M features) are far too
sparse to densify at full scale.  The MXU cannot consume CSR directly, so
the sparse path lowers to gather + ``segment_sum`` (matvec) and a scatter-
add (rmatvec) — XLA compiles both to decent TPU code, and the row-id
layout (COO-style, not indptr) is exactly what ``segment_sum`` wants and
what shards cleanly by nnz ranges later.

``CSRMatrix`` is a pytree (arrays are leaves, shape is static aux data) so
it can close over jit/shard_map boundaries and ride inside the fused AGD
loop like any dense operand.  The loss kernels dispatch on it through
``ops.losses.matvec``/``rmatvec`` — the same ``Gradient`` classes serve
dense and sparse data.

TPU layout note — the CSC twin: the gradient product ``X.T @ mult`` is a
scatter-add over *unsorted* column ids, and unsorted scatter is the one
sparse primitive TPUs lower badly (serialized updates).  ``with_csc()``
builds a second, column-sorted copy of the entries at data-placement
time; ``rmatvec`` then becomes the same sorted ``segment_sum`` shape the
forward product already uses — trading ~2x entry memory for a sorted
reduction on the hot gradient path.  Both copies are inert padding-safe
and produce identical sums up to f32 reassociation.  ``rows_sorted``
marks layouts whose ``row_ids`` are nondecreasing (true for
``from_csr_arrays``) so the forward ``segment_sum`` can claim
``indices_are_sorted`` too.

Padding contract: ``nnz`` may include padding entries (value 0.0) so
nnz-sharded layouts can be rectangular; zero values contribute nothing to
either product regardless of which row/col slot they point at.  Sorted
layouts put padding at the LAST row/col slot to keep ids nondecreasing.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_pytree_node_class
class CSRMatrix:
    """Row-sparse matrix in COO-with-row-ids form.

    ``row_ids``/``col_ids``/``values`` are (nnz,) arrays; ``shape`` is
    static.  Build from scipy-style CSR via ``from_csr_arrays``.  An
    optional column-sorted twin (``csc_*``, see module docstring) serves
    the transpose products; build it with ``with_csc()``.
    """

    def __init__(self, row_ids, col_ids, values, shape: Tuple[int, int],
                 *, csc_row_ids=None, csc_col_ids=None, csc_values=None,
                 rows_sorted: bool = False, want_csc: bool = False):
        self.row_ids = row_ids
        self.col_ids = col_ids
        self.values = values
        self.shape = tuple(shape)
        self.csc_row_ids = csc_row_ids
        self.csc_col_ids = csc_col_ids
        self.csc_values = csc_values
        self.rows_sorted = bool(rows_sorted)
        self.want_csc = bool(want_csc)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return ((self.row_ids, self.col_ids, self.values,
                 self.csc_row_ids, self.csc_col_ids, self.csc_values),
                (self.shape, self.rows_sorted, self.want_csc))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, rows_sorted, want_csc = aux
        rid, cid, val, crid, ccid, cval = leaves
        return cls(rid, cid, val, shape, csc_row_ids=crid,
                   csc_col_ids=ccid, csc_values=cval,
                   rows_sorted=rows_sorted, want_csc=want_csc)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_csr_arrays(cls, indptr, indices, values, n_features: int,
                        with_csc: bool = False) -> "CSRMatrix":
        indptr = np.asarray(indptr)
        n_rows = len(indptr) - 1
        counts = np.diff(indptr)
        row_ids = np.repeat(np.arange(n_rows, dtype=np.int32), counts)
        indices = np.asarray(indices, np.int32)
        values = np.asarray(values)
        csc = {}
        if with_csc:  # sort on host, before any device transfer
            order = np.argsort(indices, kind="stable")
            csc = dict(csc_row_ids=jnp.asarray(row_ids[order]),
                       csc_col_ids=jnp.asarray(indices[order]),
                       csc_values=jnp.asarray(values[order]))
        return cls(jnp.asarray(row_ids), jnp.asarray(indices),
                   jnp.asarray(values), (n_rows, int(n_features)),
                   rows_sorted=True, **csc)

    def with_csc(self, lazy: bool = False) -> "CSRMatrix":
        """Return a copy carrying the column-sorted twin of the entries.

        ``lazy=True`` only MARKS the matrix as wanting the twin
        (``want_csc``); materialization is deferred to data placement —
        ``Gradient.prepare`` builds it for single-device runs, while
        ``mesh.shard_csr_batch`` reads the flag and builds per-shard
        twins directly, never paying for a global one it would discard.

        Eager builds sort once at placement time, never inside a
        compiled program, and match the residency of the source arrays:
        host-numpy entries sort on the host and get a host-numpy twin;
        device entries sort ON DEVICE (``jnp.argsort``) — the twin is
        built where the data lives, with no host round-trip over the
        (possibly slow) host↔device link.
        """
        if self.has_csc or (lazy and self.want_csc):
            return self
        if lazy:
            return CSRMatrix(self.row_ids, self.col_ids, self.values,
                             self.shape, rows_sorted=self.rows_sorted,
                             want_csc=True)
        if isinstance(self.values, jax.Array):
            order = jnp.argsort(self.col_ids, stable=True)
            return CSRMatrix(
                self.row_ids, self.col_ids, self.values, self.shape,
                csc_row_ids=jnp.take(self.row_ids, order),
                csc_col_ids=jnp.take(self.col_ids, order),
                csc_values=jnp.take(self.values, order),
                rows_sorted=self.rows_sorted)
        cid = np.asarray(self.col_ids)
        order = np.argsort(cid, kind="stable")
        return CSRMatrix(
            self.row_ids, self.col_ids, self.values, self.shape,
            csc_row_ids=np.asarray(self.row_ids)[order],
            csc_col_ids=cid[order],
            csc_values=np.asarray(self.values)[order],
            rows_sorted=self.rows_sorted)

    @property
    def has_csc(self) -> bool:
        return self.csc_values is not None

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz(self) -> int:
        return int(self.values.shape[0])

    # -- products ----------------------------------------------------------
    def matvec(self, w):
        """``X @ w`` -> (n_rows,): gather + segment-sum over rows."""
        prods = self.values * jnp.take(w, self.col_ids, axis=0)
        return jax.ops.segment_sum(prods, self.row_ids,
                                   num_segments=self.shape[0],
                                   indices_are_sorted=self.rows_sorted)

    def rmatvec(self, v):
        """``X.T @ v`` -> (n_features,): sorted segment-sum over columns
        when the CSC twin is present, else scatter-add.  Output dtype
        follows promotion rules, matching the dense ``X.T @ v``."""
        if self.has_csc:
            contrib = self.csc_values * jnp.take(v, self.csc_row_ids,
                                                 axis=0)
            return jax.ops.segment_sum(
                contrib, self.csc_col_ids, num_segments=self.shape[1],
                indices_are_sorted=True)
        contrib = self.values * jnp.take(v, self.row_ids, axis=0)
        out_dt = jnp.result_type(self.values, v)
        return jnp.zeros(self.shape[1], out_dt).at[self.col_ids].add(contrib)

    def matmat(self, W):
        """``X @ W`` for (D, K) dense W -> (n_rows, K)."""
        prods = self.values[:, None] * jnp.take(W, self.col_ids, axis=0)
        return jax.ops.segment_sum(prods, self.row_ids,
                                   num_segments=self.shape[0],
                                   indices_are_sorted=self.rows_sorted)

    def rmatmat(self, V):
        """``X.T @ V`` for (n_rows, K) dense V -> (n_features, K)."""
        if self.has_csc:
            contrib = self.csc_values[:, None] * jnp.take(
                V, self.csc_row_ids, axis=0)
            return jax.ops.segment_sum(
                contrib, self.csc_col_ids, num_segments=self.shape[1],
                indices_are_sorted=True)
        contrib = self.values[:, None] * jnp.take(V, self.row_ids, axis=0)
        out_dt = jnp.result_type(self.values, V)
        return jnp.zeros((self.shape[1], V.shape[1]),
                         out_dt).at[self.col_ids].add(contrib)


@jax.tree_util.register_pytree_node_class
class RowShardedCSR:
    """A CSR batch laid out for the mesh ``data`` axis (sparse DP).

    The reference's distributed pass works on any RDD of sparse vectors
    (``Gradient.compute`` takes a ``Vector``, reference
    ``AcceleratedGradientDescent.scala:196-204``); this is the TPU layout
    that restores that capability for mesh parallelism.  Rows are assigned
    to shards (nnz-balanced by default — see ``parallel.mesh.
    shard_csr_batch``), each shard's entries are re-indexed to LOCAL row
    ids and padded to a common ``nnz_per_shard`` so the stacked arrays are
    rectangular; inside ``shard_map`` every device reconstructs its slice
    as an ordinary :class:`CSRMatrix` of shape ``(rows_per_shard, D)`` —
    one sparse kernel implementation serves every layout.

    ``row_ids``/``col_ids``/``values`` are ``(n_shards * nnz_per_shard,)``
    device arrays sharded over the data axis; padding entries are value
    0.0 pointing at the last local row / col slot (inert in both
    products, and id-order-preserving — see the module padding contract).
    ``shape`` is the GLOBAL logical shape (unpadded row count); per-shard
    row slots beyond the real rows carry mask 0 in the accompanying
    ``ShardedBatch.mask``.  ``csc_*``, when present, is each shard's
    column-sorted entry copy (``mesh.shard_csr_batch`` builds it when the
    input carries one).
    """

    def __init__(self, row_ids, col_ids, values, shape: Tuple[int, int],
                 rows_per_shard: int, n_shards: int,
                 *, csc_row_ids=None, csc_col_ids=None, csc_values=None,
                 rows_sorted: bool = False):
        self.row_ids = row_ids
        self.col_ids = col_ids
        self.values = values
        self.shape = tuple(shape)
        self.rows_per_shard = int(rows_per_shard)
        self.n_shards = int(n_shards)
        self.csc_row_ids = csc_row_ids
        self.csc_col_ids = csc_col_ids
        self.csc_values = csc_values
        self.rows_sorted = bool(rows_sorted)

    def tree_flatten(self):
        return ((self.row_ids, self.col_ids, self.values,
                 self.csc_row_ids, self.csc_col_ids, self.csc_values),
                (self.shape, self.rows_per_shard, self.n_shards,
                 self.rows_sorted))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        shape, rows_per_shard, n_shards, rows_sorted = aux
        rid, cid, val, crid, ccid, cval = leaves
        return cls(rid, cid, val, shape=shape,
                   rows_per_shard=rows_per_shard, n_shards=n_shards,
                   csc_row_ids=crid, csc_col_ids=ccid, csc_values=cval,
                   rows_sorted=rows_sorted)

    @property
    def has_csc(self) -> bool:
        return self.csc_values is not None

    @property
    def sharding(self):
        """The values array's sharding (all three leaves are placed
        identically) — lets ``api.run`` recover the mesh the same way it
        does from a dense ``ShardedBatch.X``."""
        return self.values.sharding

    @property
    def nnz_per_shard(self) -> int:
        return int(self.values.shape[0]) // self.n_shards

    def local_csr(self, row_ids, col_ids, values,
                  csc_row_ids=None, csc_col_ids=None,
                  csc_values=None) -> CSRMatrix:
        """Reassemble ONE shard's slice (as seen inside ``shard_map``)
        into a local CSRMatrix of shape ``(rows_per_shard, D)``, carrying
        the shard's CSC twin when the layout has one."""
        return CSRMatrix(row_ids, col_ids, values,
                         (self.rows_per_shard, self.shape[1]),
                         csc_row_ids=csc_row_ids, csc_col_ids=csc_col_ids,
                         csc_values=csc_values,
                         rows_sorted=self.rows_sorted)


def matvec(X, w):
    """Polymorphic ``X @ w`` (dense array or CSRMatrix) used by the loss
    kernels; 2-D ``w`` routes to matmat."""
    if isinstance(X, CSRMatrix):
        return X.matmat(w) if w.ndim == 2 else X.matvec(w)
    return X @ w


def rmatvec(X, v):
    """Polymorphic ``X.T @ v``."""
    if isinstance(X, CSRMatrix):
        return X.rmatmat(v) if v.ndim == 2 else X.rmatvec(v)
    return X.T @ v

