"""Pallas TPU kernels: fused loss+grad with single-pass HBM traffic.

Why hand-write a kernel when XLA already fuses elementwise tails into
matmuls?  Because the smooth evaluation (the reference's ``applySmooth``
hot loop, ``AcceleratedGradientDescent.scala:196-204``) is HBM-bandwidth
bound, and its XLA lowering reads the (N, D) data matrix TWICE per call:
once for ``margins = X @ w`` and once for ``grad = X.T @ multipliers``.
The fused kernel below streams each row-block of X into VMEM once and
computes *both* MXU products plus the VPU elementwise math before moving
on — halving the dominant memory traffic.  The grid walks row-blocks
sequentially (TPU grids are sequential per core), accumulating the scalar
loss in SMEM and the (1, D) gradient partial in a VMEM block that every
grid step revisits.

Numerics: inputs are consumed as given (f32, or bf16 riding the MXU's
native mixed-precision path); all accumulation is f32 via
``preferred_element_type`` — same contract as the jnp kernels under
default TPU matmul precision.  Parity with ``losses.LogisticGradient`` is
pinned in ``tests/test_pallas.py``.

Off-TPU (CPU tests, debugging) the same kernel runs in interpreter mode —
slow but bit-faithful enough for parity tests; ``PallasLogisticGradient``
falls back to the pure-jnp kernel for CSR inputs, which have their own
layout (``ops.sparse``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .losses import Gradient, LogisticGradient, _count
from .sparse import CSRMatrix

# Row-block size: 512 rows x D_pad cols of f32 must fit VMEM (~16 MB)
# comfortably alongside the w / grad blocks; 512 x 4096 x 4 B = 8 MB.
_BLOCK_ROWS = 512
_LANE = 128  # last-dim tile width for f32


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _logistic_kernel(x_ref, y_ref, m_ref, w_ref, loss_ref, grad_ref):
    """One row-block: margins, per-row loss, multipliers, and BOTH matmuls
    off a single VMEM-resident X block."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        loss_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    xb = x_ref[:]  # (BN, Dp) — read once, used twice
    # margins = -(x . w), MLlib 1.3 sign convention (losses.py)
    margins = -jnp.dot(xb, w_ref[:],
                       preferred_element_type=jnp.float32)  # (BN, 1)
    y = y_ref[:].astype(jnp.float32)  # (BN, 1)
    m = m_ref[:].astype(jnp.float32)  # (BN, 1) — 0 for padding rows
    per = (jax.nn.softplus(margins) - (1.0 - y) * margins) * m
    mult = (jax.nn.sigmoid(-margins) - y) * m

    loss_ref[0, 0] += jnp.sum(per)
    # grad partial = mult^T @ X -> (1, Dp), contracting the BN rows
    grad_ref[:] += jax.lax.dot_general(
        mult, xb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("interpret", "block_rows"))
def fused_logistic_loss_grad(w, X, y, mask=None, *, interpret=False,
                             block_rows=_BLOCK_ROWS):
    """``(loss_sum, grad_sum)`` of the binary logistic loss, one HBM pass.

    ``X (N, D)`` dense, ``w (D,)``, ``y (N,)`` in {0,1}, optional ``mask
    (N,)``.  Shapes are padded to TPU tiles internally (mask 0 rows / zero
    columns are exact no-ops in both products).
    """
    n, d = X.shape
    np_, dp = _pad_to(n, block_rows), _pad_to(d, _LANE)
    in_dt = X.dtype
    # bf16 X rides the MXU natively; anything else computes in f32
    if in_dt not in (jnp.bfloat16, jnp.float32):
        X = X.astype(jnp.float32)
        in_dt = jnp.float32
    Xp = jnp.zeros((np_, dp), in_dt).at[:n, :d].set(X)
    wp = jnp.zeros((dp, 1), jnp.float32).at[:d, 0].set(
        w.astype(jnp.float32))
    yp = jnp.zeros((np_, 1), jnp.float32).at[:n, 0].set(
        y.astype(jnp.float32))
    ones = jnp.ones((n,), jnp.float32) if mask is None else \
        mask.astype(jnp.float32)
    mp = jnp.zeros((np_, 1), jnp.float32).at[:n, 0].set(ones)

    grid = np_ // block_rows
    loss, grad = pl.pallas_call(
        _logistic_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((block_rows, dp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((dp, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, dp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * np_ * dp,  # two (BN,Dp) matmul passes per block
            bytes_accessed=np_ * dp * X.dtype.itemsize + 3 * np_ * 4,
            transcendentals=2 * np_,
        ),
        interpret=interpret,
    )(Xp, yp, mp, wp)
    return loss[0, 0], grad[0, :d]


class PallasLogisticGradient(LogisticGradient):
    """Drop-in ``LogisticGradient`` whose dense path uses the fused Pallas
    kernel (CSR inputs fall back to the jnp/segment-sum path).

    ``interpret=None`` auto-selects: compiled on TPU, interpreter
    elsewhere (tests).
    """

    def __init__(self, interpret=None, block_rows: int = _BLOCK_ROWS):
        self._interpret = (jax.default_backend() != "tpu"
                           if interpret is None else bool(interpret))
        self._block_rows = int(block_rows)

    def batch_loss_and_grad(self, weights, X, y, mask=None):
        if isinstance(X, CSRMatrix):
            return super().batch_loss_and_grad(weights, X, y, mask)
        loss, grad = fused_logistic_loss_grad(
            weights, X, y, mask, interpret=self._interpret,
            block_rows=self._block_rows)
        dt = jnp.result_type(weights)
        return loss.astype(dt), grad.astype(dt), _count(X, mask)
