"""Pallas TPU kernels: fused loss+grad with single-pass HBM traffic.

Why hand-write a kernel when XLA already fuses elementwise tails into
matmuls?  Because the smooth evaluation (the reference's ``applySmooth``
hot loop, ``AcceleratedGradientDescent.scala:196-204``) is HBM-bandwidth
bound, and its XLA lowering reads the (N, D) data matrix TWICE per call:
once for ``margins = X @ w`` and once for ``grad = X.T @ multipliers``.
The fused kernel below streams each row-block of X into VMEM once and
computes *both* MXU products plus the VPU elementwise middle before
moving on — halving the dominant memory traffic.  The grid walks
row-blocks sequentially (TPU grids are sequential per core), accumulating
the scalar loss in SMEM and the (1, D) gradient partial in a VMEM block
that every grid step revisits.

Width scaling (VERDICT r1: the old fixed 512-row block capped D at ~4k
before VMEM overflow): the row-block height now ADAPTS to the feature
width — ``choose_block_rows`` sizes the block so the double-buffered X
stream plus the full-width w and gradient-accumulator rows fit a VMEM
budget (default 12 MB of the ~16 MB/core).  At rcv1 width (D≈47k, f32)
that gives 32-row blocks; bf16 doubles it.  The single-pass design
fundamentally requires a FULL-width row block resident in VMEM (the
elementwise middle is a nonlinear function of the complete row dot, so a
D-tiled second product would have to re-read X — the very traffic this
kernel exists to delete).  Beyond the width where even 8 rows no longer
fit (~180k f32 features), ``PallasMarginGradient`` falls back to the XLA
two-pass lowering, which at that point has equal HBM traffic anyway.

Generality: any :class:`~spark_agd_tpu.ops.losses.MarginGradient` runs
through the same kernel — the per-row middle is the SAME
``dots_loss_and_mult`` seam the jnp and feature-sharded paths use
(losses.py:105-128), so logistic, least-squares, and hinge cannot drift
across implementations.

HBM residency (ADVICE r1): padding operands per call would either re-pad
per smooth evaluation or keep a hoisted second full-size copy live.  The
fix is ``prepare()``: the smooth factory (``core.smooth.make_smooth``)
pads ONCE, eagerly, at data-placement time into a :class:`PaddedDense`,
and the fused loop closes over the padded operands only.

Numerics: inputs are consumed as given (f32, or bf16 riding the MXU's
native mixed-precision path); all accumulation is f32 via
``preferred_element_type`` — same contract as the jnp kernels under
default TPU matmul precision.  Parity with the jnp kernels is pinned in
``tests/test_pallas.py``; compiled-mode parity at rcv1 width runs in
``tpu_checks.py`` (needs the real chip).

Off-TPU (CPU tests, debugging) the same kernel runs in interpreter mode —
slow but bit-faithful enough for parity tests; CSR inputs fall back to
the jnp/segment-sum path, which has its own layout (``ops.sparse``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .losses import Gradient, LogisticGradient, MarginGradient, _count
from .sparse import CSRMatrix

_LANE = 128  # last-dim tile width for f32
_SUBLANE = 8  # second-minor granularity for f32
# VMEM working-set budget: leave headroom under the ~16 MB/core for the
# pipeline's own bookkeeping and the y/mask blocks.
_VMEM_BUDGET = 12 * 2**20
_MAX_BLOCK_ROWS = 512


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def choose_block_rows(d_padded: int, itemsize: int,
                      vmem_budget: int = _VMEM_BUDGET,
                      fixed_bytes: Optional[int] = None,
                      row_extra_bytes: int = 0) -> int:
    """Largest sublane-aligned row-block height whose working set fits
    the VMEM budget: 2 double-buffered (rows, Dp) X blocks plus
    ``fixed_bytes`` of block-independent panels (default: the margin
    kernel's f32 w column + gradient-accumulator row) plus
    ``row_extra_bytes`` per block row (kernel temporaries wider than a
    lane, e.g. the softmax kernel's (BN, Kp) intermediates).  Returns 0
    when even the minimum 8-row block cannot fit (caller falls back to
    XLA)."""
    if fixed_bytes is None:
        fixed_bytes = 2 * d_padded * 4  # w (Dp,1) + grad acc (1,Dp), f32
    avail = vmem_budget - fixed_bytes
    if avail <= 0:
        return 0
    rows = avail // (2 * d_padded * itemsize + row_extra_bytes)
    rows = min(_MAX_BLOCK_ROWS, (rows // _SUBLANE) * _SUBLANE)
    return int(rows) if rows >= _SUBLANE else 0


@jax.tree_util.register_pytree_node_class
class PaddedDense:
    """Dense operands padded once to TPU tiles at data-placement time.

    ``X (Np, Dp)``, ``y (Np, 1)`` f32, ``m (Np, 1)`` f32 (0 = padding or
    caller-masked row), ``n_valid`` the 0-d valid-row count, and the
    logical pre-pad shape (STATIC aux data — jit slices need them as
    Python ints).  Built by :func:`pad_dense`; consumed by
    :func:`fused_margin_loss_grad` and recognized by
    ``PallasMarginGradient.batch_loss_and_grad``.
    """

    def __init__(self, X, y, m, n_valid, n_rows: int, n_features: int):
        self.X = X
        self.y = y
        self.m = m
        self.n_valid = n_valid
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)

    def tree_flatten(self):
        return ((self.X, self.y, self.m, self.n_valid),
                (self.n_rows, self.n_features))

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, *aux)


def pad_dense(X, y, mask=None, *, block_rows: Optional[int] = None
              ) -> PaddedDense:
    """Pad (X, y, mask) to tile boundaries — call ONCE, outside the
    optimizer loop (the smooth factory does).  Padding rows/columns are
    exact no-ops in both MXU products (zeros with mask 0)."""
    n, d = X.shape
    dp = _pad_to(d, _LANE)
    if X.dtype not in (jnp.bfloat16, jnp.float32):
        X = X.astype(jnp.float32)
    br = block_rows or choose_block_rows(dp, X.dtype.itemsize)
    if br == 0:
        raise ValueError(
            f"feature width {d} (padded {dp}) exceeds the single-pass "
            f"VMEM ceiling; use the XLA path (PallasMarginGradient does "
            f"this fall-back automatically)")
    np_ = _pad_to(n, br)
    Xp = jnp.zeros((np_, dp), X.dtype).at[:n, :d].set(X)
    yp = jnp.zeros((np_, 1), jnp.float32).at[:n, 0].set(
        jnp.asarray(y).astype(jnp.float32))
    ones = jnp.ones((n,), jnp.float32) if mask is None else \
        jnp.asarray(mask).astype(jnp.float32)
    mp = jnp.zeros((np_, 1), jnp.float32).at[:n, 0].set(ones)
    n_valid = _count(X, mask)
    return PaddedDense(Xp, yp, mp, n_valid, n, d)


def _margin_kernel(middle, x_ref, y_ref, m_ref, w_ref, loss_ref, grad_ref):
    """One row-block: dots, the per-row loss/multiplier middle, and BOTH
    MXU products off a single VMEM-resident X block."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        loss_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    xb = x_ref[:]  # (BN, Dp) — read once, used twice
    dots = jnp.dot(xb, w_ref[:],
                   preferred_element_type=jnp.float32)  # (BN, 1)
    y = y_ref[:].astype(jnp.float32)  # (BN, 1)
    m = m_ref[:].astype(jnp.float32)  # (BN, 1) — 0 for padding rows
    # THE margin-form seam (losses.MarginGradient.dots_loss_and_mult):
    # identical code to the jnp and feature-sharded paths.
    per, mult = middle(dots, y)
    per = per * m
    mult = mult * m

    loss_ref[0, 0] += jnp.sum(per)
    # grad partial = mult^T @ X -> (1, Dp), contracting the BN rows
    grad_ref[:] += jax.lax.dot_general(
        mult, xb, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("gradient", "interpret", "block_rows"))
def fused_margin_loss_grad(gradient: MarginGradient, w, padded: PaddedDense,
                           *, interpret=False,
                           block_rows: Optional[int] = None):
    """``(loss_sum, grad_sum)`` of any margin-form GLM loss, one HBM pass.

    ``padded`` comes from :func:`pad_dense` (built once, outside the
    loop).  ``block_rows`` defaults to the VMEM-budgeted choice for the
    padded width and dtype.
    """
    Xp, yp, mp = padded.X, padded.y, padded.m
    np_, dp = Xp.shape
    br = block_rows or choose_block_rows(dp, Xp.dtype.itemsize)
    if br == 0 or np_ % br:
        raise ValueError(
            f"padded rows {np_} not divisible by block_rows {br}; "
            f"pad_dense and fused_margin_loss_grad must agree on the "
            f"block size")
    kernel = functools.partial(_margin_kernel,
                               gradient.dots_loss_and_mult)
    wp = jnp.zeros((dp, 1), jnp.float32).at[:padded.n_features, 0].set(
        jnp.asarray(w).astype(jnp.float32))

    grid = np_ // br
    loss, grad = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, dp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((dp, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, dp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, dp), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * np_ * dp,  # two MXU passes per resident block
            bytes_accessed=np_ * dp * Xp.dtype.itemsize + 3 * np_ * 4,
            transcendentals=2 * np_,
        ),
        interpret=interpret,
    )(Xp, yp, mp, wp)
    return loss[0, 0], grad[0, :padded.n_features]


# Singleton for the back-compat wrapper: fused_margin_loss_grad caches by
# the gradient's identity (static jit arg), so a fresh instance per call
# would recompile the kernel every time.
_LOGISTIC = LogisticGradient()


def fused_logistic_loss_grad(w, X, y, mask=None, *, interpret=False,
                             block_rows: Optional[int] = None):
    """Back-compat wrapper: logistic ``(loss_sum, grad_sum)`` from RAW
    dense operands (pads in-trace — prefer ``pad_dense`` +
    ``fused_margin_loss_grad`` outside benchmarks/tests)."""
    padded = pad_dense(X, y, mask, block_rows=block_rows)
    return fused_margin_loss_grad(
        _LOGISTIC, w, padded, interpret=interpret,
        block_rows=block_rows)


class PallasMarginGradient(MarginGradient):
    """Drop-in wrapper running any :class:`MarginGradient` through the
    fused single-HBM-pass kernel on dense data.

    - ``prepare()`` (called once by the smooth factory) pads operands
      eagerly so the fused loop never re-pads (ADVICE r1).
    - CSR inputs, over-wide features (past the VMEM ceiling), and raw
      TRACER inputs fall back to the wrapped jnp kernel.  The tracer
      fallback is deliberate: a tracer means the call site skipped
      ``prepare``, and padding in-trace would re-stage the full matrix
      every smooth evaluation of the compiled loop — strictly worse
      than XLA's two-pass lowering.  Mesh data parallelism does NOT hit
      this fallback: ``parallel.dist_smooth`` recognizes the wrapper
      and relays the batch out once into per-shard tile-aligned slices
      (``_make_shard_map_pallas``), so the fused kernel runs inside the
      shard_map body.
    - ``interpret=None`` auto-selects: compiled on TPU, interpreter
      elsewhere (tests).
    """

    def __init__(self, inner: MarginGradient, interpret=None,
                 block_rows: Optional[int] = None):
        if not isinstance(inner, MarginGradient):
            raise TypeError(
                "PallasMarginGradient wraps margin-form GLM losses "
                f"(MarginGradient); got {type(inner).__name__}")
        self.inner = inner
        self._interpret = (jax.default_backend() != "tpu"
                           if interpret is None else bool(interpret))
        self._block_rows = block_rows

    # the MarginGradient contract, delegated — so margin-seam consumers
    # (e.g. parallel.feature_sharded) accept the wrapper directly
    def dots_loss_and_mult(self, dots, y):
        return self.inner.dots_loss_and_mult(dots, y)

    def _supported_width(self, d: int, itemsize: int) -> bool:
        dp = _pad_to(d, _LANE)
        return (self._block_rows or
                choose_block_rows(dp, itemsize)) >= _SUBLANE

    def prepare(self, X, y, mask=None):
        """Eager one-time padding for the smooth factory.  Returns the
        ``(X, y, mask)`` triple contract with ``X`` a PaddedDense and the
        labels/mask folded in (``None``)."""
        if isinstance(X, CSRMatrix):
            # sparse falls back to the wrapped jnp kernel — run the base
            # staging (materializes a lazily-requested CSC twin)
            return super().prepare(X, y, mask)
        if isinstance(X, PaddedDense) or isinstance(X, jax.core.Tracer):
            return X, y, mask
        X = jnp.asarray(X)
        itemsize = 2 if X.dtype == jnp.bfloat16 else 4
        if X.ndim != 2 or not self._supported_width(X.shape[1], itemsize):
            return X, y, mask
        return pad_dense(X, y, mask, block_rows=self._block_rows), None, None

    def batch_loss_and_grad(self, weights, X, y, mask=None):
        if isinstance(X, PaddedDense):
            loss, grad = fused_margin_loss_grad(
                self.inner, weights, X, interpret=self._interpret,
                block_rows=self._block_rows)
            dt = jnp.result_type(weights)
            return loss.astype(dt), grad.astype(dt), X.n_valid
        if isinstance(X, CSRMatrix) or isinstance(X, jax.core.Tracer) \
                or X.ndim != 2 \
                or not self._supported_width(
                    X.shape[1],
                    2 if X.dtype == jnp.bfloat16 else 4):
            # tracer = un-prepared call inside a compiled program: in-trace
            # padding would re-stage X per evaluation — use the XLA path
            return self.inner.batch_loss_and_grad(weights, X, y, mask)
        padded = pad_dense(X, y, mask, block_rows=self._block_rows)
        loss, grad = fused_margin_loss_grad(
            self.inner, weights, padded, interpret=self._interpret,
            block_rows=self._block_rows)
        dt = jnp.result_type(weights)
        return loss.astype(dt), grad.astype(dt), _count(X, mask)


class PallasLogisticGradient(PallasMarginGradient):
    """Logistic specialization (the round-1 name, kept for benchmarks and
    call sites that predate the margin-general kernel)."""

    def __init__(self, interpret=None, block_rows: Optional[int] = None):
        super().__init__(LogisticGradient(), interpret=interpret,
                         block_rows=block_rows)


# ---------------------------------------------------------------------------
# Fused softmax: the (D, K)-weight multinomial loss (BASELINE config 4)
# through the same single-HBM-pass design as the margin kernel.
# ---------------------------------------------------------------------------

def choose_block_rows_softmax(d_padded: int, k_padded: int, itemsize: int,
                              vmem_budget: int = _VMEM_BUDGET) -> int:
    """Row-block height for the softmax kernel's working set: beyond the
    X stream, the full (Dp, Kp) f32 weight AND gradient-accumulator
    panels are block-independent, and ~4 (BN, Kp) f32 intermediates
    (logits / ez / onehot / resid) are live per block row."""
    return choose_block_rows(
        d_padded, itemsize, vmem_budget,
        fixed_bytes=2 * d_padded * k_padded * 4,
        row_extra_bytes=4 * k_padded * 4)


def _softmax_kernel(num_classes, x_ref, y_ref, m_ref, w_ref, loss_ref,
                    grad_ref):
    """One row-block: logits, a stable masked logsumexp, and BOTH MXU
    products off a single VMEM-resident X block.  Class padding columns
    (Kp > K) carry -inf logits so they vanish from the softmax; their
    residuals are exactly 0, so the (Dp, Kp) gradient tail stays zero."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        loss_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    xb = x_ref[:]  # (BN, Dp) — read once, used twice
    logits = jnp.dot(xb, w_ref[:],
                     preferred_element_type=jnp.float32)  # (BN, Kp)
    kp = logits.shape[1]
    class_ids = jax.lax.broadcasted_iota(jnp.float32, (1, kp), 1)
    valid_cls = class_ids < num_classes  # (1, Kp)
    neg_inf = jnp.float32(-jnp.inf)
    logits = jnp.where(valid_cls, logits, neg_inf)
    zmax = jnp.max(logits, axis=1, keepdims=True)  # (BN, 1)
    ez = jnp.where(valid_cls, jnp.exp(logits - zmax), 0.0)
    sez = jnp.sum(ez, axis=1, keepdims=True)
    lse = zmax + jnp.log(sez)  # (BN, 1)

    y = y_ref[:]  # (BN, 1) f32 integral labels
    m = m_ref[:]  # (BN, 1) f32, 0 for padding rows
    onehot = jnp.where(class_ids == y, 1.0, 0.0)  # (BN, Kp)
    # select-then-sum, NOT logits*onehot: padding classes hold -inf and
    # 0 * -inf would poison the sum with NaN
    picked = jnp.sum(jnp.where(onehot > 0, logits, 0.0), axis=1,
                     keepdims=True)
    per = (lse - picked) * m
    resid = (ez / sez - onehot) * m  # (BN, Kp); 0 on padding classes

    loss_ref[0, 0] += jnp.sum(per)
    # grad partial = X^T @ resid -> (Dp, Kp), contracting the BN rows
    grad_ref[:] += jax.lax.dot_general(
        xb, resid, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("num_classes", "interpret",
                                   "block_rows"))
def fused_softmax_loss_grad(num_classes: int, W, padded: PaddedDense, *,
                            interpret=False,
                            block_rows: Optional[int] = None):
    """``(loss_sum, grad_sum)`` of the multinomial softmax, one HBM pass.

    ``padded`` comes from :func:`pad_dense` built with
    ``choose_block_rows_softmax`` blocks (labels ride the f32 ``y``
    plane); ``W`` is the logical (D, K) weight matrix.
    """
    Xp, yp, mp = padded.X, padded.y, padded.m
    np_, dp = Xp.shape
    kp = _pad_to(num_classes, _LANE)
    br = block_rows or choose_block_rows_softmax(dp, kp,
                                                 Xp.dtype.itemsize)
    if br == 0 or np_ % br:
        raise ValueError(
            f"padded rows {np_} not divisible by softmax block_rows {br}")
    kernel = functools.partial(_softmax_kernel, num_classes)
    Wp = jnp.zeros((dp, kp), jnp.float32).at[
        :padded.n_features, :num_classes].set(
        jnp.asarray(W).astype(jnp.float32))

    grid = np_ // br
    loss, grad = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((br, dp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((dp, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((dp, kp), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((dp, kp), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * np_ * dp * kp,
            bytes_accessed=np_ * dp * Xp.dtype.itemsize + 3 * np_ * 4,
            transcendentals=2 * np_ * kp,
        ),
        interpret=interpret,
    )(Xp, yp, mp, Wp)
    return loss[0, 0], grad[:padded.n_features, :num_classes]


class PallasSoftmaxGradient(Gradient):
    """Drop-in fused-kernel wrapper for :class:`~spark_agd_tpu.ops.
    losses.SoftmaxGradient` on dense data (BASELINE config 4).

    Same staging contract as :class:`PallasMarginGradient`: ``prepare``
    pads once at data-placement time; CSR, over-wide, and un-prepared
    tracer inputs fall back to the wrapped jnp kernel.
    """

    def __init__(self, inner, interpret=None,
                 block_rows: Optional[int] = None):
        from .losses import SoftmaxGradient

        if not isinstance(inner, SoftmaxGradient):
            raise TypeError(
                "PallasSoftmaxGradient wraps SoftmaxGradient; got "
                f"{type(inner).__name__}")
        self.inner = inner
        self.num_classes = inner.num_classes
        self._interpret = (jax.default_backend() != "tpu"
                           if interpret is None else bool(interpret))
        self._block_rows = block_rows

    def _block(self, d: int, itemsize: int) -> int:
        dp = _pad_to(d, _LANE)
        kp = _pad_to(self.num_classes, _LANE)
        return self._block_rows or choose_block_rows_softmax(dp, kp,
                                                             itemsize)

    def prepare(self, X, y, mask=None):
        if isinstance(X, CSRMatrix):
            return super().prepare(X, y, mask)
        if isinstance(X, PaddedDense) or isinstance(X, jax.core.Tracer):
            return X, y, mask
        X = jnp.asarray(X)
        itemsize = 2 if X.dtype == jnp.bfloat16 else 4
        if X.ndim != 2 or self._block(X.shape[1], itemsize) < _SUBLANE:
            return X, y, mask
        return (pad_dense(X, y, mask,
                          block_rows=self._block(X.shape[1], itemsize)),
                None, None)

    def batch_loss_and_grad(self, weights, X, y, mask=None):
        if isinstance(X, PaddedDense):
            loss, grad = fused_softmax_loss_grad(
                self.num_classes, weights, X, interpret=self._interpret,
                block_rows=self._block(X.n_features,
                                       X.X.dtype.itemsize))
            dt = jnp.result_type(weights)
            return loss.astype(dt), grad.astype(dt), X.n_valid
        return self.inner.batch_loss_and_grad(weights, X, y, mask)
