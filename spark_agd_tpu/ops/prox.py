"""Proximal operators — the TPU-native ``Updater`` contract.

The reference delegates its proximity step to spark-mllib ``Updater.compute
(weightsOld, gradient, stepSize, iter, regParam)`` and always passes
``iter = 1`` to defeat MLlib's hidden ``stepSize/sqrt(iter)`` rescaling
(reference ``AcceleratedGradientDescent.scala:214-222``).  It also reads the
regularization value *without moving the weights* by calling the updater with
``step = 0.0`` (reference ``:305``).

This module makes both contracts explicit instead of implicit:

- ``prox(w, g, step, reg) -> (w_new, reg_value)`` with **no** hidden
  step rescaling (the rescaling belongs to the SGD driver, see
  ``core/gd.py``), and
- a separate ``reg_value(w, reg)`` so "read the penalty at w" never needs the
  ``step = 0`` trick — though the identity ``prox(w, g, 0) == (w,
  reg_value(w))`` is still guaranteed and tested, because the fused AGD loop
  relies on it for loss-history accounting.

``reg_value`` conventions match spark-mllib 1.3.0 (pin at reference
``build.sbt:7``): L2 returns the penalty at the *new* weights
``reg/2·‖w'‖²``; L1 returns ``reg·‖w'‖₁``.  All operators map leafwise over
pytrees, so the same prox drives a GLM vector or an MLP parameter tree.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import tvec


class Prox:
    """Protocol: proximity operator of a (possibly zero) penalty.

    Equivalent of the spark-mllib ``Updater`` abstract class as consumed at
    reference ``AcceleratedGradientDescent.scala:215-220``, minus the
    ``iter`` rescaling foot-gun.
    """

    def prox(self, w, g, step, reg):
        """Return ``(w_new, reg_value_at_w_new)``.

        Must satisfy ``prox(w, g, 0.0, reg) == (w, reg_value(w, reg))``.
        """
        raise NotImplementedError

    def reg_value(self, w, reg):
        raise NotImplementedError

    def smooth_penalty(self, w, reg):
        """``(value, grad)`` of the penalty at ``w`` — or ``None`` when
        the penalty is not differentiable.

        This is the seam the L-BFGS driver uses: MLlib's ``LBFGS``
        folds ``SquaredL2Updater`` regularization into its ``CostFun``
        as an added objective term (value ``reg/2·‖w‖²``, gradient
        ``reg·w``) rather than a prox step, and supports NO non-smooth
        penalty in 1.3 (OWLQN came later).  ``None`` means "prox-only
        penalty"; callers needing a smooth objective must reject it."""
        return None

    def owlqn_decomposition(self, reg):
        """``(l1_coeff, smooth_fn)`` splitting this penalty into an
        ``l1_coeff·‖w‖₁`` part (handled by OWL-QN's pseudo-gradients)
        plus a differentiable remainder ``smooth_fn(w) -> (value,
        grad)`` — or ``None`` when the penalty fits neither form.
        This is how the quasi-Newton driver covers the FULL updater
        menu: smooth penalties route to plain L-BFGS (``l1_coeff`` 0),
        L1/elastic-net to OWL-QN — the lift Spark itself applied after
        1.3 (Breeze OWLQN under ``ml``)."""
        if self.smooth_penalty(jnp.zeros(()), float(reg)) is None:
            return None
        return 0.0, lambda w: self.smooth_penalty(w, reg)


def _scalar_dtype(w):
    import jax

    leaves = jax.tree_util.tree_leaves(w)
    return jnp.result_type(*leaves) if leaves else jnp.float32


class IdentityProx(Prox):
    """No penalty: plain gradient step.  MLlib ``SimpleUpdater`` equivalent
    (reference test use-sites Suite:42, :65)."""

    def prox(self, w, g, step, reg):
        w_new = tvec.tmap(lambda wi, gi: wi - step * gi, w, g)
        return w_new, jnp.zeros((), _scalar_dtype(w))

    def reg_value(self, w, reg):
        return jnp.zeros((), _scalar_dtype(w))

    def smooth_penalty(self, w, reg):
        return jnp.zeros((), _scalar_dtype(w)), tvec.zeros_like(w)


class L2Prox(Prox):
    """EXACT prox of ``(reg/2)·‖w‖²``: ``(w - step·g) / (1 + step·reg)``.

    Note: this is the *mathematically exact* proximity operator (what TFOCS
    theory assumes), NOT what spark-mllib 1.3.0's ``SquaredL2Updater``
    computes — that one is a linearized step; see ``MLlibSquaredL2Updater``
    below, which is what the ``SquaredL2Updater`` parity alias points at.
    Penalty is evaluated at the new weights (the MLlib reg-value convention,
    kept for both variants)."""

    def prox(self, w, g, step, reg):
        shrink = 1.0 / (1.0 + step * reg)
        w_new = tvec.tmap(lambda wi, gi: (wi - step * gi) * shrink, w, g)
        return w_new, self.reg_value(w_new, reg)

    def reg_value(self, w, reg):
        return 0.5 * reg * tvec.sq_norm(w)

    def smooth_penalty(self, w, reg):
        # differentiable: value reg/2·‖w‖², gradient reg·w — exactly
        # MLlib LBFGS CostFun's L2 handling (inherited by the
        # MLlib-faithful subclass: the CostFun term is the same even
        # though the Updater's prox step is linearized)
        return self.reg_value(w, reg), tvec.scale(reg, w)


class MLlibSquaredL2Updater(L2Prox):
    """Bit-faithful spark-mllib 1.3.0 ``SquaredL2Updater`` semantics.

    MLlib does NOT apply the exact prox: it takes a gradient step on the
    regularized objective, ``w' = (1 - step·reg)·w - step·g`` (per the 1.3.0
    source comment "w' = w - thisIterStepSize * (gradient + regParam * w)"),
    with ``reg_value = reg/2·‖w'‖²`` at the NEW weights.  This is what the
    reference actually executed through ``applyProjector`` (reference
    ``AcceleratedGradientDescent.scala:215-220``; test use-sites Suite:43,
    :107, :251), so oracle/parity tests use this class.  It agrees with the
    exact prox only to first order in ``step·reg``; the ``step = 0``
    identity still holds exactly."""

    def prox(self, w, g, step, reg):
        w_new = tvec.tmap(
            lambda wi, gi: (1.0 - step * reg) * wi - step * gi, w, g)
        return w_new, self.reg_value(w_new, reg)


class L1Prox(Prox):
    """Prox of ``reg·‖w‖₁``: soft-thresholding by ``step·reg``.  MLlib
    ``L1Updater`` equivalent (BASELINE config 3)."""

    def prox(self, w, g, step, reg):
        thresh = step * reg

        def soft(wi, gi):
            v = wi - step * gi
            return jnp.sign(v) * jnp.maximum(jnp.abs(v) - thresh, 0.0)

        w_new = tvec.tmap(soft, w, g)
        return w_new, self.reg_value(w_new, reg)

    def reg_value(self, w, reg):
        return reg * tvec.l1_norm(w)

    def owlqn_decomposition(self, reg):
        zero = lambda w: (jnp.zeros((), _scalar_dtype(w)),
                          tvec.zeros_like(w))
        return float(reg), zero


class ElasticNetProx(Prox):
    """Prox of ``reg·(l1_ratio·‖w‖₁ + (1-l1_ratio)/2·‖w‖²)``.

    Beyond the reference's menu (capability extension): the closed-form
    sequential composition soft-threshold-then-shrink, exact for this
    separable penalty.
    """

    def __init__(self, l1_ratio: float = 0.5):
        self.l1_ratio = float(l1_ratio)

    def prox(self, w, g, step, reg):
        l1 = reg * self.l1_ratio
        l2 = reg * (1.0 - self.l1_ratio)
        thresh = step * l1
        shrink = 1.0 / (1.0 + step * l2)

        def op(wi, gi):
            v = wi - step * gi
            return jnp.sign(v) * jnp.maximum(jnp.abs(v) - thresh, 0.0) * shrink

        w_new = tvec.tmap(op, w, g)
        return w_new, self.reg_value(w_new, reg)

    def reg_value(self, w, reg):
        l1 = reg * self.l1_ratio
        l2 = reg * (1.0 - self.l1_ratio)
        return l1 * tvec.l1_norm(w) + 0.5 * l2 * tvec.sq_norm(w)

    def owlqn_decomposition(self, reg):
        l2 = reg * (1.0 - self.l1_ratio)
        smooth = lambda w: (0.5 * l2 * tvec.sq_norm(w),
                            tvec.scale(l2, w))
        return float(reg * self.l1_ratio), smooth


# API-parity aliases (the names user code migrating from the reference knows).
# SquaredL2Updater deliberately maps to the MLlib-faithful linearized variant,
# not the exact prox — migrating users get the trajectory they had.
SimpleUpdater = IdentityProx
SquaredL2Updater = MLlibSquaredL2Updater
L1Updater = L1Prox

PROXES = {
    "identity": IdentityProx,
    "l2": L2Prox,
    "l2_mllib": MLlibSquaredL2Updater,
    "l1": L1Prox,
    "elastic_net": ElasticNetProx,
}
