"""shard_map compatibility shim.

The distributed layer targets the stable ``jax.shard_map`` API
(``check_vma=`` keyword).  Older jaxlib toolchains (such as the 0.4.x
pin this container bakes in) only ship the experimental spelling
(``jax.experimental.shard_map.shard_map`` with ``check_rep=``).  Every
in-tree use routes through this one wrapper so the version split lives
in exactly one place.
"""

from __future__ import annotations

try:  # jax >= 0.6: stable top-level API
    from jax import shard_map as _shard_map

    _NEW_API = True
except ImportError:  # the 0.4.x experimental spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _NEW_API = False


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with the stable keyword surface on every
    supported jax version (``check_vma`` maps onto the old
    ``check_rep``; both toggle the same replication check)."""
    if _NEW_API:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
