"""Mesh construction and data/weight placement.

The reference's distribution model (SURVEY §3.2): weights broadcast
driver→executors per evaluation, partial (loss, grad, count) tree-reduced
executors→driver — 4-6+ full weight transfers per outer iteration.  The
TPU-native model this module implements: a ``jax.sharding.Mesh`` whose
``data`` axis shards example rows across chips and whose optional ``model``
axis shards wide weight matrices (softmax classes / MLP hidden units); the
weight pytree is *replicated* into every chip's HBM once and updated in
place on-chip, so the broadcast disappears entirely (SURVEY §2.2
"broadcast → eliminated").

On real hardware the mesh axes ride ICI; in tests the same code runs on 8
virtual CPU devices (``tests/conftest.py``) — the ``MLlibTestSparkContext``
analogue, with real shardings and real collectives.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


class ShardedBatch(NamedTuple):
    """A mesh-placed (X, y, mask) triple.  Pass this whole object to
    ``make_dist_smooth`` — the mask travels with the data it pads, so the
    silently-wrong-mean trap of discarding it can't happen by accident."""

    X: jax.Array
    y: jax.Array
    mask: Optional[jax.Array]  # None iff no padding and caller gave none


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices=None) -> Mesh:
    """Build a named mesh.  ``axes`` maps axis name → size (e.g. ``{"data":
    4, "model": 2}``); ``None`` puts every device on the ``data`` axis —
    pure DP, the reference's only strategy (SURVEY §2.3)."""
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh axes {axes} need {need} devices, have {len(devices)}")
    dev_array = np.array(devices[:need]).reshape(sizes)
    return Mesh(dev_array, names)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place a weight pytree replicated into every device's HBM — the
    one-time cost that deletes the reference's per-evaluation broadcast
    (reference ``:193``)."""
    sh = NamedSharding(mesh, P())
    return jax.device_put(tree, sh)


def shard_batch(
    mesh: Mesh,
    X,
    y,
    mask=None,
    axis: str = DATA_AXIS,
) -> ShardedBatch:
    """Shard (X, y) rows over ``axis``, padding to an even per-device split.

    Returns a ``ShardedBatch``; its ``mask`` is None when no padding was
    needed and the caller passed none.  Padding rows are zeros
    with mask 0, which the kernels exclude from every sum
    (``ops.losses._as_mask``) — so a 10,001-row dataset on 8 chips computes
    exactly the 10,001-row answer.  This is the RDD-partitioning analogue
    (reference Suite:51 ``sc.parallelize(data, 2)``), minus the skew: every
    shard is the same size by construction.
    """
    X = np.asarray(X) if not isinstance(X, jax.Array) else X
    y = np.asarray(y) if not isinstance(y, jax.Array) else y
    n = X.shape[0]
    ndev = mesh.shape[axis]
    rem = (-n) % ndev
    if rem:
        pad_x = np.zeros((rem,) + tuple(X.shape[1:]), dtype=X.dtype)
        pad_y = np.zeros((rem,) + tuple(y.shape[1:]), dtype=y.dtype)
        base_mask = (np.ones(n, dtype=np.float32) if mask is None
                     else np.asarray(mask, dtype=np.float32))
        X = np.concatenate([np.asarray(X), pad_x])
        y = np.concatenate([np.asarray(y), pad_y])
        mask = np.concatenate([base_mask, np.zeros(rem, np.float32)])
    row_sharding = NamedSharding(mesh, P(axis))
    Xs = jax.device_put(X, NamedSharding(mesh, P(axis, *([None] * (X.ndim - 1)))))
    ys = jax.device_put(y, row_sharding)
    ms = None if mask is None else jax.device_put(
        np.asarray(mask), row_sharding)
    return ShardedBatch(Xs, ys, ms)
