"""Mesh construction and data/weight placement.

The reference's distribution model (SURVEY §3.2): weights broadcast
driver→executors per evaluation, partial (loss, grad, count) tree-reduced
executors→driver — 4-6+ full weight transfers per outer iteration.  The
TPU-native model this module implements: a ``jax.sharding.Mesh`` whose
``data`` axis shards example rows across chips and whose optional ``model``
axis shards wide weight matrices (softmax classes / MLP hidden units); the
weight pytree is *replicated* into every chip's HBM once and updated in
place on-chip, so the broadcast disappears entirely (SURVEY §2.2
"broadcast → eliminated").

On real hardware the mesh axes ride ICI; in tests the same code runs on 8
virtual CPU devices (``tests/conftest.py``) — the ``MLlibTestSparkContext``
analogue, with real shardings and real collectives.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import native
from ..ops.sparse import CSRMatrix, RowShardedCSR

DATA_AXIS = "data"
MODEL_AXIS = "model"


class ShardedBatch(NamedTuple):
    """A mesh-placed (X, y, mask) triple.  Pass this whole object to
    ``make_dist_smooth`` — the mask travels with the data it pads, so the
    silently-wrong-mean trap of discarding it can't happen by accident."""

    X: jax.Array
    y: jax.Array
    mask: Optional[jax.Array]  # None iff no padding and caller gave none


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices=None) -> Mesh:
    """Build a named mesh.  ``axes`` maps axis name → size (e.g. ``{"data":
    4, "model": 2}``); ``None`` puts every device on the ``data`` axis —
    pure DP, the reference's only strategy (SURVEY §2.3)."""
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh axes {axes} need {need} devices, have {len(devices)}")
    dev_array = np.array(devices[:need]).reshape(sizes)
    return Mesh(dev_array, names)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place a weight pytree replicated into every device's HBM — the
    one-time cost that deletes the reference's per-evaluation broadcast
    (reference ``:193``)."""
    sh = NamedSharding(mesh, P())
    return jax.device_put(tree, sh)


def shard_batch(
    mesh: Mesh,
    X,
    y,
    mask=None,
    axis: str = DATA_AXIS,
) -> ShardedBatch:
    """Shard (X, y) rows over ``axis``, padding to an even per-device split.

    Returns a ``ShardedBatch``; its ``mask`` is None when no padding was
    needed and the caller passed none.  Padding rows are zeros
    with mask 0, which the kernels exclude from every sum
    (``ops.losses._as_mask``) — so a 10,001-row dataset on 8 chips computes
    exactly the 10,001-row answer.  This is the RDD-partitioning analogue
    (reference Suite:51 ``sc.parallelize(data, 2)``), minus the skew: every
    shard is the same size by construction.
    """
    if isinstance(X, CSRMatrix):
        return shard_csr_batch(mesh, X, y, mask, axis=axis)
    X = np.asarray(X) if not isinstance(X, jax.Array) else X
    y = np.asarray(y) if not isinstance(y, jax.Array) else y
    n = X.shape[0]
    ndev = mesh.shape[axis]
    rem = (-n) % ndev
    if rem:
        pad_x = np.zeros((rem,) + tuple(X.shape[1:]), dtype=X.dtype)
        pad_y = np.zeros((rem,) + tuple(y.shape[1:]), dtype=y.dtype)
        base_mask = (np.ones(n, dtype=np.float32) if mask is None
                     else np.asarray(mask, dtype=np.float32))
        X = np.concatenate([np.asarray(X), pad_x])
        y = np.concatenate([np.asarray(y), pad_y])
        mask = np.concatenate([base_mask, np.zeros(rem, np.float32)])
    row_sharding = NamedSharding(mesh, P(axis))
    Xs = jax.device_put(X, NamedSharding(mesh, P(axis, *([None] * (X.ndim - 1)))))
    ys = jax.device_put(y, row_sharding)
    ms = None if mask is None else jax.device_put(
        np.asarray(mask), row_sharding)
    return ShardedBatch(Xs, ys, ms)


def shard_batch_by_features(
    mesh: Mesh,
    X,
    y,
    mask=None,
    axis: str = MODEL_AXIS,
) -> ShardedBatch:
    """Shard a DENSE batch's feature columns over ``axis`` (dense D-axis
    parallelism — the dense twin of ``feature_sharded``'s CSR layout).

    Consume with ``make_dist_smooth(..., mode="auto")`` and weights
    placed by :func:`shard_weights_by_features` (which zero-pads to the
    batch's width): GSPMD keeps the optimizer state D-sharded end to end
    and inserts the one (N,)-margin reduction itself — pinned by
    ``tests/test_parallel.py::TestDenseFeatureSharding``.  Columns pad
    with zeros to an even split; a pad column is inert ONLY while its
    weight slot is zero (zero gradient + every prox in ``ops.prox``
    fixing 0 keeps it there) — weights that start nonzero in the pad
    tail would silently leak regularization, which is why the weight
    helper owns the padding.
    """
    if isinstance(X, CSRMatrix):
        raise ValueError(
            "shard_batch_by_features is the DENSE D-axis layout; for "
            "sparse data use parallel.feature_sharded."
            "shard_csr_by_columns")
    X = np.asarray(X) if not isinstance(X, jax.Array) else X
    d = X.shape[1]
    k = mesh.shape[axis]
    rem = (-d) % k
    if rem:
        X = np.concatenate(
            [np.asarray(X),
             np.zeros((X.shape[0], rem), dtype=X.dtype)], axis=1)
    rep = NamedSharding(mesh, P())
    Xs = jax.device_put(X, NamedSharding(mesh, P(None, axis)))
    ys = jax.device_put(np.asarray(y) if not isinstance(y, jax.Array)
                        else y, rep)
    ms = None if mask is None else jax.device_put(
        np.asarray(mask, np.float32), rep)
    return ShardedBatch(Xs, ys, ms)


def shard_weights_by_features(w, batch: ShardedBatch, mesh: Mesh,
                              axis: str = MODEL_AXIS):
    """Place a (D,) (or (D, K)) weight array for a
    :func:`shard_batch_by_features` batch: zero-pad the feature dim to
    the batch's padded width (keeping the pad slots inert — see the
    batch builder's contract) and shard it over ``axis``.  Invert with
    :func:`unshard_weights_by_features`."""
    w = np.asarray(w)
    d_pad = batch.X.shape[1]
    if w.shape[0] > d_pad:
        raise ValueError(f"weights width {w.shape[0]} exceeds the "
                         f"batch's padded feature width {d_pad}")
    wp = np.zeros((d_pad,) + w.shape[1:], w.dtype)
    wp[:w.shape[0]] = w
    return jax.device_put(
        wp, NamedSharding(mesh, P(axis, *([None] * (w.ndim - 1)))))


def unshard_weights_by_features(w_sharded, d: int) -> np.ndarray:
    """Recover the unpadded (d, ...) weights from a D-sharded state (the
    dense twin of ``feature_sharded.unshard_weights``; the pad tail is
    exact zeros by the inert-column contract)."""
    return np.asarray(w_sharded)[:d]


def shard_csr_batch(
    mesh: Mesh,
    X: CSRMatrix,
    y,
    mask=None,
    axis: str = DATA_AXIS,
    balance: bool = True,
    nnz_per_shard: Optional[int] = None,
    extras: Optional[Dict[str, Any]] = None,
    extras_fill=-1,
) -> "ShardedBatch | Tuple[ShardedBatch, Dict[str, jax.Array]]":
    """Shard a CSR batch's ROWS over the mesh ``axis`` (sparse DP).

    This is the sparse twin of :func:`shard_batch` — the capability the
    reference gets for free from Spark (its ``treeAggregate`` pass accepts
    sparse MLlib vectors, reference ``AcceleratedGradientDescent.scala:
    196-204``) and VERDICT r1 flagged as the missing parallelism mode for
    the rcv1/url_combined configs.

    Layout: rows are assigned to shards nnz-balanced (``balance=True``,
    default — heaviest row onto the currently lightest shard; the loss /
    gradient / count sums are row-permutation-invariant, so the answer is
    unchanged) or in contiguous blocks (``balance=False``).  Each shard's
    entries are re-indexed to LOCAL row ids, sorted by local row, and
    padded to one common per-shard nnz (inert 0.0 entries pointing at the
    last row/col slot, keeping ids nondecreasing for the sorted
    segment-sums); row slots beyond a shard's real rows carry mask 0 so
    the kernels exclude them from every sum — the exact-mean contract of
    :func:`shard_batch` holds.  When ``X`` carries a CSC twin
    (``CSRMatrix.with_csc``), each shard also gets its column-sorted
    entry copy so the mesh gradient path uses sorted reductions too.

    Returns a ``ShardedBatch`` whose ``X`` is a
    :class:`~spark_agd_tpu.ops.sparse.RowShardedCSR`; its ``mask`` is
    always present (padding slots must be masked).

    ``nnz_per_shard`` pins the padded per-shard entry count instead of
    deriving it from this batch — the streaming path passes one budget
    for EVERY macro-batch so all batches share a single compiled kernel
    shape.  Raises ``ValueError`` when the batch cannot fit the budget.

    ``extras``: optional dict of per-row arrays (each ``(n_rows,)``, in
    the INPUT row order) to carry through the nnz-balancing permutation
    alongside ``y`` — e.g. cross-validation fold ids.  When given, the
    return value is ``(ShardedBatch, placed_extras)`` where each placed
    extra is row-sharded exactly like the batch's ``y``; padding slots
    read ``extras_fill`` (and carry mask 0 regardless).
    """
    n_rows, n_features = X.shape
    if n_rows == 0:
        raise ValueError("cannot shard an empty CSR batch")
    row_ids = np.asarray(X.row_ids)
    col_ids = np.asarray(X.col_ids)
    values = np.asarray(X.values)
    if nnz_per_shard is not None:
        # Streamed macro-batches arrive pre-padded with inert 0.0 entries
        # piled onto the LAST row slot (iter_csr_batches contract); fed
        # to the balancer they masquerade as one enormous row and blow
        # the budget.  Zero entries contribute nothing to either product
        # (ops.sparse padding contract), so drop them before balancing —
        # each shard re-pads to the budget below anyway.
        keep = values != 0
        if not keep.all():
            row_ids, col_ids, values = (row_ids[keep], col_ids[keep],
                                        values[keep])
    lay = csr_shard_layout(
        row_ids, col_ids, values, np.asarray(y), mask, n_rows,
        n_features, mesh.shape[axis], balance=balance,
        with_csc=X.has_csc or X.want_csc, nnz_per_shard=nnz_per_shard,
        extras=extras, extras_fill=extras_fill)
    batch = place_csr_layout(lay, mesh, axis, n_rows, n_features)
    if extras is None:
        return batch
    spec = NamedSharding(mesh, P(axis))
    # flatten only the (shard, slot) leading dims — an (n_rows, k)
    # extra keeps its trailing shape, rows sharded like y
    placed = {name: jax.device_put(
                  lay["E_" + name].reshape(
                      (-1,) + lay["E_" + name].shape[2:]), spec)
              for name in extras}
    return batch, placed


def csr_shard_layout(row_ids, col_ids, values, y, mask, n_rows: int,
                     n_features: int, n_shards: int, *,
                     balance: bool = True, with_csc: bool = False,
                     nnz_per_shard: Optional[int] = None,
                     reduce_max=None,
                     extras: Optional[Dict[str, Any]] = None,
                     extras_fill=-1) -> dict:
    """Pure-host (NumPy) construction of the per-shard CSR layout — the
    core of :func:`shard_csr_batch`, factored out so multi-host ingest
    (``data.ingest.from_partitioned_files_csr``) can build each host's
    LOCAL shards with GLOBALLY-agreed dimensions.

    ``reduce_max(int) -> int`` equalizes the two cross-host dimensions
    (rows-per-shard before balancing, padded nnz-per-shard after) — pass
    an allgather-max under SPMD; identity (default) single-process.
    Returns ``dict(R, C, V[, Rc, Cc, Vc], Y, M, rps, nnz_shard)`` with
    2-D ``(n_shards, ...)`` arrays ready to flatten and place.
    """
    for name, arr in (extras or {}).items():
        # validate before the (expensive at url_combined scale) balance
        # + sort + pad work below, so a wrong-length extra fails free
        # graftlint: disable=host-sync -- one-shot staging loop over a
        # handful of named host-numpy extras, not a per-iteration loop
        if np.asarray(arr).shape[:1] != (n_rows,):
            raise ValueError(
                f"extras[{name!r}] has "
                # graftlint: disable=host-sync -- same staging loop
                f"{np.asarray(arr).shape[0] if np.asarray(arr).ndim else 0}"
                f" rows, expected {n_rows}")
    red = reduce_max or (lambda v: int(v))
    rps = red(max(1, -(-n_rows // n_shards) if n_rows else 1))

    if n_rows:
        counts = np.bincount(row_ids, minlength=n_rows)
        if balance:
            # Greedy nnz balance (same scheme as the column layout in
            # feature_sharded.py): heaviest row onto the lightest shard
            # with remaining capacity.  Bounds the padded per-shard nnz
            # near max(heaviest row, total/n_shards).  C++ core
            # (native.greedy_balance) with a bit-identical Python
            # fallback — the heapq loop costs seconds at url_combined
            # scale (native measured 7x faster at 3.2M items).
            shard_of_row, local_of_row = native.greedy_balance(
                counts, n_shards, rps)
        else:
            rows = np.arange(n_rows, dtype=np.int64)
            shard_of_row = rows // rps
            local_of_row = rows % rps
    else:  # a host with no partitions still participates in the layout
        shard_of_row = np.zeros(0, np.int64)
        local_of_row = np.zeros(0, np.int64)

    e_shard = shard_of_row[row_ids]
    e_local = local_of_row[row_ids].astype(np.int32)
    eorder = np.argsort(e_shard, kind="stable")
    shard_sorted = e_shard[eorder]
    starts = np.searchsorted(shard_sorted, np.arange(n_shards))
    ends = np.searchsorted(shard_sorted, np.arange(n_shards), side="right")
    nnz_needed = max(int((ends - starts).max()) if len(values) else 1, 1)
    if nnz_per_shard is not None:
        if nnz_needed > nnz_per_shard:
            raise ValueError(
                f"a shard holds {nnz_needed} entries > nnz_per_shard="
                f"{nnz_per_shard}; raise the budget (streaming callers: "
                f"make_streaming_smooth's csr_nnz_per_shard — one "
                f"compiled shape must fit every macro-batch)")
        nnz_shard = int(nnz_per_shard)
    else:
        nnz_shard = red(nnz_needed)

    # Padding slots point at the LAST local row / col (inert 0.0 values)
    # so per-shard ids stay nondecreasing and both segment-sums can claim
    # ``indices_are_sorted`` (see ops.sparse module docstring).
    R = np.full((n_shards, nnz_shard), rps - 1, np.int32)
    C = np.zeros((n_shards, nnz_shard), np.int32)
    V = np.zeros((n_shards, nnz_shard), values.dtype)
    out = dict(R=R, C=C, V=V, rps=rps, nnz_shard=nnz_shard)
    if with_csc:
        Rc = np.zeros((n_shards, nnz_shard), np.int32)
        Cc = np.full((n_shards, nnz_shard), n_features - 1, np.int32)
        Vc = np.zeros((n_shards, nnz_shard), values.dtype)
        out.update(Rc=Rc, Cc=Cc, Vc=Vc)
    for s in range(n_shards):
        sel = eorder[starts[s]:ends[s]]
        # row-sorted copy: order the shard's entries by local row id
        sel_r = sel[np.argsort(e_local[sel], kind="stable")]
        k = len(sel)
        R[s, :k] = e_local[sel_r]
        C[s, :k] = col_ids[sel_r]
        V[s, :k] = values[sel_r]
        if with_csc:  # column-sorted twin of the same entries
            sel_c = sel[np.argsort(col_ids[sel], kind="stable")]
            Rc[s, :k] = e_local[sel_c]
            Cc[s, :k] = col_ids[sel_c]
            Vc[s, :k] = values[sel_c]

    Y = np.zeros((n_shards, rps), y.dtype if n_rows else np.float32)
    M = np.zeros((n_shards, rps), np.float32)
    if n_rows:
        Y[shard_of_row, local_of_row] = y
        M[shard_of_row, local_of_row] = (
            np.ones(n_rows, np.float32) if mask is None
            else np.asarray(mask, np.float32))
    out.update(Y=Y, M=M)
    # Per-row extras (e.g. CV fold ids) scatter along the SAME
    # (shard, local-slot) assignment as y, so anything keyed to input
    # rows survives the nnz-balancing permutation aligned to the batch.
    for name, arr in (extras or {}).items():
        # graftlint: disable=host-sync -- one-shot staging scatter over
        # a handful of named host-numpy extras (shape validated up front)
        arr = np.asarray(arr)
        E = np.full((n_shards, rps) + arr.shape[1:], extras_fill,
                    arr.dtype)
        if n_rows:
            E[shard_of_row, local_of_row] = arr
        out["E_" + name] = E
    return out


def place_csr_layout(lay: dict, mesh: Mesh, axis: str, n_rows: int,
                      n_features: int) -> ShardedBatch:
    """Device-place a single-process :func:`csr_shard_layout` result."""
    spec = NamedSharding(mesh, P(axis))
    csc = {}
    if "Rc" in lay:
        csc = dict(
            csc_row_ids=jax.device_put(lay["Rc"].reshape(-1), spec),
            csc_col_ids=jax.device_put(lay["Cc"].reshape(-1), spec),
            csc_values=jax.device_put(lay["Vc"].reshape(-1), spec))
    Xs = RowShardedCSR(
        row_ids=jax.device_put(lay["R"].reshape(-1), spec),
        col_ids=jax.device_put(lay["C"].reshape(-1), spec),
        values=jax.device_put(lay["V"].reshape(-1), spec),
        shape=(n_rows, n_features), rows_per_shard=lay["rps"],
        n_shards=lay["R"].shape[0], rows_sorted=True, **csc)
    return ShardedBatch(Xs, jax.device_put(lay["Y"].reshape(-1), spec),
                        jax.device_put(lay["M"].reshape(-1), spec))
