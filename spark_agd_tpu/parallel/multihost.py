"""Multi-host / multi-slice meshes: ICI inside a slice, DCN between.

The reference scales out by adding Spark executors over the network (Netty
RPC; SURVEY §5 "Distributed communication backend").  The TPU-native
equivalent is structural, not a transport library: every host runs the
same program (`jax.distributed` SPMD), the mesh enumerates *global*
devices, and XLA routes each collective over ICI within a slice and DCN
across slices based on the mesh layout.  The one thing the user must get
right is that layout — DCN is an order of magnitude slower than ICI, so
axes that carry heavy collectives (the AGD gradient psum) must map to ICI
and only the low-traffic axis (e.g. macro-batch data replicas) to DCN.
``make_hybrid_mesh`` encodes exactly that.

Single-host processes (tests, the one-chip bench) fall back to a plain
mesh over the visible devices, so code written against this module runs
unchanged from laptop CPU to multi-slice pods.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np
import jax
from jax.sharding import Mesh

from . import mesh as mesh_lib


def _already_initialized() -> bool:
    """State check (not string matching): has jax.distributed joined a
    job in this process already?"""
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 — private API moved; fall through
        return False


def _backends_initialized() -> bool:
    """State check: has any XLA backend come up?  (jax.distributed must
    run before that; this is the condition its own ordering error
    tests.)"""
    try:
        from jax._src import xla_bridge

        return xla_bridge.backends_are_initialized()
    except Exception:  # noqa: BLE001 — private API moved
        return False


def launcher_markers() -> list:
    """Environment markers indicating this process is PART OF a
    multi-process launch (a cluster launcher, MPI, SLURM, or a multi-
    worker TPU pod).  In such a context a skipped ``initialize`` would
    silently produce N independent single-host runs — wrong results, no
    error (ADVICE r1 #1) — so the no-op fallback must not trigger."""
    env = os.environ
    found = []
    for k in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        if env.get(k):
            found.append(k)
    hosts = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",")
             if h.strip()]
    if len(hosts) > 1:
        found.append("TPU_WORKER_HOSTNAMES")
    # NB: only launcher-owned variables belong here — e.g. NPROC is a
    # common user convention for core count and must NOT be a marker.
    for k in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        v = env.get(k, "")
        if v.isdigit() and int(v) > 1:
            found.append(k)
    return found


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host SPMD job (idempotent).  On TPU pods with a
    supported launcher the arguments are auto-detected; pass them
    explicitly elsewhere.  After this, ``jax.devices()`` is global and
    every host must execute the same compiled programs (the driver/executor
    asymmetry of the reference does not exist here)."""
    explicit = any(a is not None for a in (coordinator_address,
                                           num_processes, process_id))
    if _already_initialized():
        return  # second call — idempotent
    if _backends_initialized():
        # Too late to join: a backend already came up.  In a genuinely
        # single-process context a bare call is a harmless no-op; inside
        # a multi-process launch (or with explicit args) degrading to N
        # independent runs is the silent-wrong-results failure mode, so
        # it must surface loudly.
        markers = launcher_markers()
        if explicit or markers:
            raise RuntimeError(
                "jax.distributed.initialize must run before any JAX "
                "computation, but a backend is already initialized in "
                "this process"
                + (f"; multi-process launcher environment detected "
                   f"({', '.join(markers)})" if markers else "")
                + ". Move multihost.initialize() to program start.")
        return
    try:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    except RuntimeError as e:
        # Backstops should the private state checks above degrade across
        # JAX versions: keep the idempotent-second-call contract, and keep
        # the bare-call-after-backend no-op for genuinely single-process
        # contexts (explicit args / launcher markers still re-raise).
        msg = str(e).lower()
        if "already" in msg:
            return
        if not explicit and not launcher_markers() \
                and ("before any jax" in msg or "computation" in msg):
            return
        raise
    except ValueError:
        if explicit or launcher_markers():
            # The caller (or the launch environment) wanted a multi-host
            # job; silently degrading to N independent single-process
            # runs would produce wrong results with no error.
            raise
        # bare initialize() in a single-process run (tests / one chip):
        # nothing to join


def make_hybrid_mesh(ici_axes: Dict[str, int],
                     dcn_axes: Optional[Dict[str, int]] = None) -> Mesh:
    """Mesh whose per-axis size is ``ici * dcn``, laid out so the ICI
    factor is contiguous within a slice.

    ``make_hybrid_mesh({"data": 4, "model": 2}, {"data": 2})`` on 2 slices
    of 8 chips: gradient psums ride ICI inside each slice; only the
    2-way data-replica reduction crosses DCN.  Falls back to a plain
    ``make_mesh`` when the topology has no slice structure (CPU tests,
    single slice) — same axis names and sizes, so calling code never
    branches.
    """
    dcn_axes = dcn_axes or {}
    names = list(dict.fromkeys(list(ici_axes) + list(dcn_axes)))
    ici = [ici_axes.get(n, 1) for n in names]
    dcn = [dcn_axes.get(n, 1) for n in names]
    total = {n: i * d for n, i, d in zip(names, ici, dcn)}
    devices = jax.devices()
    # Fall back on TOPOLOGY, not on exceptions: a misconfigured spec on a
    # real multi-slice pod must raise, not silently return a plain mesh
    # whose heavy collectives span DCN.
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    if len(slice_ids) <= 1:
        # no slice structure (CPU tests / single slice): plain mesh with
        # the same axis names and sizes, so calling code never branches
        return mesh_lib.make_mesh(total)
    from jax.experimental import mesh_utils

    devs = mesh_utils.create_hybrid_device_mesh(ici, dcn, devices=devices)
    return Mesh(devs, tuple(names))


def is_primary_host() -> bool:
    """True on the one process that should own singleton side effects
    (rank-0 telemetry sinks, checkpoint writes, artifact emission).
    Trivially True in a single-process run, so gated code needs no
    single-host special case."""
    return jax.process_index() == 0


def process_tag() -> str:
    """A short per-host tag for file names — ``""`` on a single host
    (so single-host paths are untouched), ``"h003"``-style on a
    multi-process job."""
    if jax.process_count() <= 1:
        return ""
    return f"h{jax.process_index():03d}"


def host_suffixed(path: str) -> str:
    """``path`` with this host's tag spliced in before the extension
    (``run.jsonl`` → ``run.h003.jsonl``) — the per-host-sink convention
    of ``obs``: every host streams, no two hosts share a file.  Identity
    on a single host."""
    tag = process_tag()
    if not tag:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.{tag}{ext}"


def local_rows_slice(n_rows: int, process_index: int,
                     process_count: int) -> slice:
    """The row range process ``process_index`` of ``process_count`` owns
    (ceil-divided blocks; the last block may be short).  Pure function of
    its arguments — the elastic-resume re-split
    (``resilience.distributed.load_for_topology``) computes assignments
    for a topology that is NOT this process's, so it cannot go through
    :func:`process_local_rows`."""
    per = -(-n_rows // process_count)
    return slice(process_index * per,
                 min((process_index + 1) * per, n_rows))


def rank_among(members, process_index: int) -> int:
    """This process's rank within an explicit member list (sorted
    original process indices) — the re-indexing a DEGRADED continuation
    needs: the survivors of a host loss keep their original indices for
    shard lookup but act as ranks ``0..len(members)-1`` for the
    re-split (``resilience.degrade``).  Pure function, same contract as
    :func:`local_rows_slice`: computable for a topology that is not
    this process's."""
    members = sorted(int(p) for p in members)
    try:
        return members.index(int(process_index))
    except ValueError:
        raise ValueError(
            f"process {process_index} is not among {members}") from None


def process_local_rows(n_rows: int) -> slice:
    """The row range this host should load — the data-loading side of
    multi-host DP (each host feeds only its local shard; ``jax.make_array_
    from_process_local_data`` assembles the global array)."""
    return local_rows_slice(n_rows, jax.process_index(),
                            jax.process_count())


def process_allgather_int64(values) -> np.ndarray:
    """Allgather one small row of NON-NEGATIVE int64s per process → a
    ``(process_count, k)`` array, row ``p`` from process ``p``.  Doubles
    as a BARRIER: the call returns only after every process has
    contributed, which is how the distributed checkpoint's commit waits
    for all shard writes.  Single-process: returns ``values[None, :]``
    without touching any collective machinery.

    Transport rides as 16-bit limbs in int32: with ``jax_enable_x64``
    off (the default) jax silently downcasts int64 to int32, which
    corrupted CRC32 values above 2**31 until the limb encoding."""
    row = np.atleast_1d(np.asarray(values, np.int64))
    if (row < 0).any():
        raise ValueError("process_allgather_int64 carries non-negative "
                         f"values only, got {row}")
    if jax.process_count() <= 1:
        return row[None, :]
    from jax.experimental import multihost_utils

    limbs = np.stack([(row >> s) & 0xFFFF for s in (0, 16, 32, 48)],
                     axis=-1).astype(np.int32)  # (k, 4)
    gathered = np.asarray(multihost_utils.process_allgather(
        limbs.reshape(-1)), np.int64)
    gathered = gathered.reshape(jax.process_count(), row.size, 4)
    out = np.zeros((jax.process_count(), row.size), np.int64)
    for i in range(4):
        out |= (gathered[:, :, i] & 0xFFFF) << (16 * i)
    return out
