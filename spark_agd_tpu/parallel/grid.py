"""Mesh-composed grid fits: sweeps and cross-validation over sharded data.

The reference's architecture runs a hyper-parameter grid as sequential
cluster jobs — each ``optimize`` call re-broadcasts weights and re-reduces
gradients over the whole cluster (reference
``AcceleratedGradientDescent.scala:128`` per job).  The single-device
``api.sweep`` / ``api.cross_validate`` already collapse the grid into one
compiled program (lanes batched by ``jax.vmap``); this module composes
that lane axis WITH the mesh's ``data`` axis, which is mandatory at
north-star scale where one device cannot hold the rows:

    rows   → sharded over the mesh ``data`` axis (DP, exactly like a
             single fit through ``parallel.dist_smooth``)
    lanes  → vmapped INSIDE the shard_map body; every lane's
             (Σloss, Σgrad, n) psum is the same collective on every
             device, so the vmapped ``lax.while_loop`` sees identical
             post-psum scalars everywhere and control flow stays
             coherent across devices (the invariant SURVEY §7 hard part
             1 demands of the backtracking loop, now per lane)

The dataset lives in HBM once per device shard, shared by every lane;
the K margin matvecs still batch onto the MXU as one
``(N/devices, D) @ (D, K)`` contraction per device — the sweep's MXU
win and the mesh's HBM win compose instead of excluding each other.

Sparse rows compose too: a ``RowShardedCSR`` batch (nnz-balanced row
sharding, ``parallel.mesh.shard_csr_batch``) reconstructs each device's
local CSR once per evaluation, outside the vmap, so the segment-sums
are shared across lanes.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .shmap import shard_map

from ..core import agd, smooth as smooth_lib, tvec
from ..ops.losses import Gradient
from ..ops.prox import Prox
from ..ops.sparse import RowShardedCSR
from . import dist_smooth, mesh as mesh_lib


def _shard_data_plumbing(X, y, mask, data_axis):
    """(args, in_specs, rebuild_local) for one row-sharded dataset.

    ``rebuild_local(*shard_args) -> (X_local, y_local, mask_local)``
    runs inside the shard_map body; for CSR it reconstructs the device's
    local matrix ONCE per evaluation (shared by all vmapped lanes)."""
    row = P(data_axis)
    if isinstance(X, RowShardedCSR):
        if mask is None:
            raise ValueError(
                "RowShardedCSR requires its padding mask; build the "
                "batch with parallel.mesh.shard_csr_batch")
        args = dist_smooth.csr_shard_args(X, y, mask)
        specs = (row,) * len(args)

        def rebuild_local(rid, cid, val, ys, ms, *csc):
            return X.local_csr(rid, cid, val, *csc), ys, ms

        return args, specs, rebuild_local
    xspec = P(data_axis, *([None] * (X.ndim - 1)))
    if mask is None:
        return ((X, y), (xspec, row),
                lambda Xs, ys: (Xs, ys, None))
    return ((X, y, mask), (xspec, row, row),
            lambda Xs, ys, ms: (Xs, ys, ms))


def _local_smooth_fns(gradient, Xl, yl, ml, data_axis, layout=None):
    """The in-body (smooth, smooth_loss) pair: per-shard kernel + psum —
    ``dist_smooth._make_shard_map``'s math, but built from ALREADY-local
    shards so it can live inside a vmapped body.

    ``layout`` (a ``parallel.sharded_update.ShardLayout``) switches the
    pair to the sharded-carry dialect of the same contract: ``w`` is the
    replica's 1/N weight shard, an ``all_gather`` materializes the full
    weights only for the kernel, and the gradient combine is the
    reduce-scatter (``dist_smooth.psum_scatter_combine``) so the returned
    mean gradient is the matching 1/N shard.  The default ``None`` keeps
    the replicated pair bit-identical for this module's sweep/CV bodies.
    """

    if layout is None:
        def smooth(w):
            ls, gs, n = gradient.batch_loss_and_grad(w, Xl, yl, ml)
            ls = lax.psum(ls, data_axis)
            gs = tvec.tmap(lambda g: lax.psum(g, data_axis), gs)
            n = lax.psum(n, data_axis)
            nf = jnp.asarray(n, ls.dtype)
            return ls / nf, tvec.scale(1.0 / nf, gs)
    else:
        def smooth(w_shard):
            w = layout.gather(w_shard, data_axis)
            ls, gs, n = gradient.batch_loss_and_grad(w, Xl, yl, ml)
            ls, gs, n = dist_smooth.psum_scatter_combine(
                ls, gs, n, data_axis, layout)
            nf = jnp.asarray(n, ls.dtype)
            return ls / nf, tvec.scale(1.0 / nf, gs)

    def smooth_loss(w):
        if layout is not None:
            w = layout.gather(w, data_axis)
        ls, _, n = gradient.batch_loss_and_grad(w, Xl, yl, ml)
        ls = lax.psum(ls, data_axis)
        n = lax.psum(n, data_axis)
        return ls / jnp.asarray(n, ls.dtype)

    return smooth, smooth_loss


def make_mesh_sweep_fit(
    gradient: Gradient,
    updater: Prox,
    batch: "mesh_lib.ShardedBatch",
    mesh: Mesh,
    cfg: "agd.AGDConfig",
    *,
    data_axis: str = mesh_lib.DATA_AXIS,
) -> Callable:
    """Compile-once ``fit(reg_params, initial_weights, warm=None)`` over
    a mesh: every regularization lane trains on the full row-sharded
    dataset, all in one program.  Results are replicated (every field of
    the batched ``AGDResult`` gains a leading K axis, as in
    ``api.sweep``)."""
    X, y, mask = batch
    args, dspecs, rebuild_local = _shard_data_plumbing(X, y, mask,
                                                       data_axis)

    def _body(regs, w0, warm, *shard_args):
        Xl, yl, ml = rebuild_local(*shard_args)
        sm, sl = _local_smooth_fns(gradient, Xl, yl, ml, data_axis)

        def fit_one(reg, w):
            px, rv = smooth_lib.make_prox(updater, reg)
            return agd.run_agd(sm, px, rv, w, cfg, smooth_loss=sl)

        def fit_one_warm(reg, w, wm):
            px, rv = smooth_lib.make_prox(updater, reg)
            return agd.run_agd(sm, px, rv, w, cfg, smooth_loss=sl,
                               warm=wm)

        if warm is None:
            return jax.vmap(fit_one, in_axes=(0, None))(regs, w0)
        return jax.vmap(fit_one_warm, in_axes=(0, None, 0))(
            regs, w0, warm)

    def _make(step_warm: bool):
        # lanes and weights replicated (P()), rows sharded; the batched
        # warm pytree (one carry per lane) is replicated too — P() is a
        # pytree prefix covering every AGDWarmState leaf
        in_specs = (P(), P()) + ((P(),) if step_warm else ()) + dspecs
        body = (_body if step_warm
                else (lambda regs, w0, *sa: _body(regs, w0, None, *sa)))
        return jax.jit(functools.partial(
            shard_map, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_vma=False)(body))

    step = _make(False)
    step_w = _make(True)

    def _place(reg_params, initial_weights):
        regs = jnp.asarray(reg_params, jnp.float32)
        if regs.ndim != 1:
            raise ValueError("reg_params must be 1-D")
        # place lanes/weights/warm explicitly (no-ops when the caller
        # pre-replicated, so a transfer-guarded fit stays transfer-free)
        regs = mesh_lib.replicate(regs, mesh)
        w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
        return regs, mesh_lib.replicate(w0, mesh)

    def fit(reg_params, initial_weights, warm=None):
        regs, w0 = _place(reg_params, initial_weights)
        if warm is None:
            return step(regs, w0, *args)
        return step_w(regs, w0, mesh_lib.replicate(warm, mesh), *args)

    # AOT introspection hook (obs.introspect.analyze_lowered): the
    # cold-path program fit() runs, lowered without executing — the
    # sharded-grid member of the program-census surface
    fit.lower = lambda reg_params, initial_weights: step.lower(
        *_place(reg_params, initial_weights), *args)
    return fit


def make_mesh_cv_fit(
    gradient: Gradient,
    updater: Prox,
    batch: "mesh_lib.ShardedBatch",
    fold_ids,
    mesh: Mesh,
    cfg: "agd.AGDConfig",
    *,
    data_axis: str = mesh_lib.DATA_AXIS,
) -> Callable:
    """Compile-once ``fit(fold_lane, reg_lane, initial_weights) ->
    (val_loss_flat, batched AGDResult)`` over a mesh — the
    ``cross_validate`` lane grid with rows sharded.

    ``fold_ids`` must be aligned to the batch's (padded) row layout and
    sharded like its rows; padded rows are excluded by the batch mask on
    BOTH the train and validation sides, exactly as in the
    single-device path.  For a ``RowShardedCSR`` batch the aligned fold
    ids come from the extras channel of the sharding itself —
    ``shard_csr_batch(..., extras={"fold_ids": fids})`` scatters them
    through the nnz-balancing row permutation (padding slots read the
    fill value, which never equals a real fold id).
    """
    X, y, mask = batch
    if isinstance(X, RowShardedCSR) and mask is None:
        raise ValueError(
            "RowShardedCSR requires its padding mask; build the batch "
            "with parallel.mesh.shard_csr_batch")
    row = P(data_axis)
    base_mask = (jnp.ones(X.shape[0], jnp.float32) if mask is None
                 else mask)
    args, dspecs, rebuild_local = _shard_data_plumbing(
        X, y, base_mask, data_axis)

    def _body(fold_lane, reg_lane, w0, fids, *shard_args):
        Xl, yl, bml = rebuild_local(*shard_args)

        def mean_loss(w, m):
            ls, _, n = gradient.batch_loss_and_grad(w, Xl, yl, m)
            ls = lax.psum(ls, data_axis)
            n = lax.psum(n, data_axis)
            nf = jnp.asarray(n, ls.dtype)
            # an empty selection must read NaN, never a perfect 0.0
            return jnp.where(n > 0, ls / jnp.maximum(nf, 1), jnp.nan)

        def fit_one(fold_k, reg):
            train_mask = bml * (fids != fold_k)
            val_mask = bml * (fids == fold_k)
            sm, sl = _local_smooth_fns(gradient, Xl, yl, train_mask,
                                       data_axis)
            px, rv = smooth_lib.make_prox(updater, reg)
            res = agd.run_agd(sm, px, rv, w0, cfg, smooth_loss=sl)
            return mean_loss(res.weights, val_mask), res

        return jax.vmap(fit_one)(fold_lane, reg_lane)

    step = jax.jit(functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(), row) + dspecs, out_specs=P(),
        check_vma=False)(_body))

    def fit(fold_lane, reg_lane, initial_weights):
        w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
        w0 = mesh_lib.replicate(w0, mesh)
        lanes = mesh_lib.replicate(
            (jnp.asarray(fold_lane, jnp.int32),
             jnp.asarray(reg_lane, jnp.float32)), mesh)
        return step(lanes[0], lanes[1], w0, fold_ids, *args)

    return fit


def shard_row_array(mesh: Mesh, arr, n_padded: int,
                    axis: str = mesh_lib.DATA_AXIS, fill=0):
    """Pad a per-row array to a batch's padded row count and shard it
    like the batch's rows (the co-sharding ``shard_batch`` applies to
    ``y``/``mask``, for caller-owned extras like CV fold ids)."""
    import numpy as np

    arr = np.asarray(arr)
    if arr.shape[0] > n_padded:
        raise ValueError(
            f"array rows {arr.shape[0]} exceed padded batch rows "
            f"{n_padded}")
    pad = n_padded - arr.shape[0]
    if pad:
        arr = np.concatenate(
            [arr, np.full((pad,) + arr.shape[1:], fill, arr.dtype)])
    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def make_mesh_lbfgs_sweep_fit(
    gradient: Gradient,
    updater: Prox,
    batch: "mesh_lib.ShardedBatch",
    mesh: Mesh,
    cfg,
    *,
    data_axis: str = mesh_lib.DATA_AXIS,
) -> Callable:
    """Compile-once ``fit(reg_params, initial_weights)`` for the
    quasi-Newton member over a mesh: K regularization lanes vmapped
    INSIDE one shard_map over row-sharded data — the L-BFGS twin of
    :func:`make_mesh_sweep_fit`.  Smooth penalties only (the updater's
    ``smooth_penalty`` must accept a traced ``reg``); each lane's
    Wolfe/convergence decisions stay coherent across devices because
    every control scalar is post-psum.
    """
    from ..core import lbfgs as lbfgs_lib

    lbfgs_lib.check_smooth_penalty(updater, 1.0)  # named error, not a
    # NoneType unpack at trace time
    X, y, mask = batch
    args, dspecs, rebuild_local = _shard_data_plumbing(X, y, mask,
                                                       data_axis)

    def _body(regs, w0, *shard_args):
        Xl, yl, ml = rebuild_local(*shard_args)
        sm, _ = _local_smooth_fns(gradient, Xl, yl, ml, data_axis)

        def fit_one(reg, w):
            def objective(wv):
                f, g = sm(wv)
                pv, pg = updater.smooth_penalty(wv, reg)
                return f + pv, tvec.add(g, pg)

            return lbfgs_lib.run_lbfgs(objective, w, cfg)

        return jax.vmap(fit_one, in_axes=(0, None))(regs, w0)

    step = jax.jit(functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P()) + dspecs,
        out_specs=P(), check_vma=False)(_body))

    def fit(reg_params, initial_weights):
        # default float dtype (f64 under x64): a lane's reg must carry
        # the same precision a solo fit's python-float reg would
        regs = jnp.asarray(reg_params, jnp.result_type(float))
        if regs.ndim != 1:
            raise ValueError("reg_params must be 1-D")
        regs = mesh_lib.replicate(regs, mesh)
        w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
        w0 = mesh_lib.replicate(w0, mesh)
        return step(regs, w0, *args)

    return fit
