"""Cross-replica sharded weight update: reduce-scatter → 1/N prox → allgather.

The replicated data-parallel mode (``parallel.dist_smooth``) all-reduces
the full-D gradient and then runs the *entire* prox/momentum/backtracking
update redundantly on every replica — N identical copies of the
``tvec.axpby`` chains, the prox, and the curvature partial sums.  Per
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv 2004.13336) the all-reduce is algebraically a
reduce-scatter followed by an all-gather, and everything *between* the two
halves — the weight update — only needs the 1/N gradient shard it received.
This module builds that execution mode for the fused AGD loop:

    kernel on local rows  →  psum_scatter(Σgrad)   [1/N shard in]
    shard-local axpby / prox / z-merge             [1/N of the FLOPs]
    scalar psums for f_y, xy_sq, dots, norms       [O(1) on the wire]
    all_gather(w shard)  →  full w for the kernel  [only where needed]

On the wire per iteration the full-D traffic is one reduce-scatter plus
one all-gather per smooth evaluation — the same bytes as the all-reduce
it replaces (which IS that pair, fused) — but the update FLOPs and the
update working set drop by 1/N, which is exactly the serial fraction the
replicated mode pays on every added replica (Gustafson: the replicated
update is work that does NOT shrink with N).  ``obs.introspect``'s
collective census shows the signature: all-reduce bytes collapse to the
scalar control plane, reduce-scatter and all-gather appear.

The whole AGD loop lives inside ONE ``shard_map`` body so the carry
(``x``, ``z`` — and the warm-start state on resume) stays sharded across
iterations; ``core.agd.run_agd(axis_name=...)`` assembles its control
scalars with cheap scalar psums so both nested ``lax.while_loop``s see
identical decisions on every replica.  Entry and exit speak *full* trees:
weights in, ``AGDResult`` with full weights/final_z out — so donation,
checkpointing (``AGDWarmState`` round-trips full trees), the supervisor's
rollback anchor, and the PR 10 scheduler's pinned-shape rebalance all
compose unchanged, and a checkpoint written by either mode resumes in the
other.

The leaf geometry is fixed by :class:`ShardLayout`: every weight leaf is
flattened, zero-padded up to a multiple of N, and split evenly.  The pad
slots are inert by the prox protocol (``prox(0, 0, step) == 0`` — the
contract ``ops.prox`` already guarantees for masked/padded entries) and
contribute zero to every psummed scalar, so the padded program computes
bit-for-bit the statistics of the unpadded one.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core import agd
from ..ops.losses import Gradient
from ..ops.sparse import RowShardedCSR
from . import grid, mesh as mesh_lib
from .shmap import shard_map


class ShardLayout(NamedTuple):
    """Static per-leaf flatten/pad/split geometry of one weight pytree.

    Everything here is trace-time constant (shapes, sizes, treedef), so
    the layout can be rebuilt from any structurally-identical tree and
    two replicas can never disagree about where a shard boundary falls.
    """

    n_shards: int
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    shard_sizes: Tuple[int, ...]  # ceil(size / n_shards) per leaf
    treedef: Any

    @classmethod
    def for_tree(cls, tree, n_shards: int) -> "ShardLayout":
        leaves = jax.tree_util.tree_leaves(tree)
        treedef = jax.tree_util.tree_structure(tree)
        shapes = tuple(tuple(leaf.shape) for leaf in leaves)
        sizes = tuple(int(math.prod(s)) for s in shapes)
        shard_sizes = tuple(-(-s // n_shards) for s in sizes)
        return cls(n_shards, shapes, sizes, shard_sizes, treedef)

    def _padded(self, leaf, size, shard):
        flat = jnp.ravel(leaf)
        pad = shard * self.n_shards - size
        return jnp.pad(flat, (0, pad)) if pad else flat

    def shard(self, tree, idx):
        """Slice replica ``idx``'s 1/N of every leaf (``idx`` may be a
        traced ``lax.axis_index``)."""
        out = []
        for leaf, size, shard in zip(jax.tree_util.tree_leaves(tree),
                                     self.sizes, self.shard_sizes):
            flat = self._padded(leaf, size, shard)
            out.append(lax.dynamic_slice(flat, (idx * shard,), (shard,)))
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def gather(self, tree, axis_name: str):
        """all_gather every shard leaf back to the full leaf shape —
        the only place full weights materialize in the sharded mode."""
        out = []
        for leaf, shape, size in zip(jax.tree_util.tree_leaves(tree),
                                     self.shapes, self.sizes):
            full = lax.all_gather(leaf, axis_name, tiled=True)
            out.append(full[:size].reshape(shape))
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def scatter(self, tree, axis_name: str):
        """Reduce-scatter every full leaf: sum across replicas, keep only
        this replica's shard — the all-reduce's cheaper left half."""
        out = []
        for leaf, size, shard in zip(jax.tree_util.tree_leaves(tree),
                                     self.sizes, self.shard_sizes):
            flat = self._padded(leaf, size, shard)
            out.append(lax.psum_scatter(flat, axis_name,
                                        scatter_dimension=0, tiled=True))
        return jax.tree_util.tree_unflatten(self.treedef, out)


class ShardedUpdateBuild:
    """The ``build`` half of the sharded mode's staged ``(build, dargs)``.

    Deliberately NOT a smooth builder: a stand-alone ``smooth(w)`` cannot
    keep the carry sharded between iterations, so calling it like the
    ``dist_smooth`` builds raises.  Consumers dispatch on the
    :meth:`make_agd_run` hook instead (``api.make_runner`` and the
    resilience supervisor's segment compiler both do), which returns the
    whole fused AGD loop as one ``shard_map``-wrapped callable with the
    same ``(carry, data_args)`` call shape as the replicated step — so
    the scheduler's pinned-shape rebalance can still swap ``dargs``
    between generations without touching the build.
    """

    def __init__(self, gradient: Gradient, X, y, mask, *, mesh: Mesh,
                 data_axis: str):
        self.gradient = gradient
        self.mesh = mesh
        self.data_axis = data_axis
        # grid's plumbing is the ONE definition of (args, in_specs,
        # local rebuild) for a row-sharded dataset, dense or CSR
        self.data_args, self._data_specs, self._rebuild_local = \
            grid._shard_data_plumbing(X, y, mask, data_axis)

    def __call__(self, *a):
        raise TypeError(
            "sharded-update staged data has no stand-alone smooth: the "
            "carry must stay sharded across iterations, so the whole AGD "
            "loop is built at once — use make_agd_run(prox, reg_value, "
            "config) (api.make_runner and the supervisor do)")

    def make_agd_run(self, prox, reg_value, config, *,
                     telemetry_cb: Callable | None = None,
                     poison: bool = False,
                     warm_entry: bool = False) -> Callable:
        """``run(carry, data_args) -> AGDResult`` over FULL weight trees.

        ``carry`` is ``w0`` (cold start) or an ``AGDWarmState`` holding
        full trees when ``warm_entry=True`` (the supervisor's resume
        path); either way the sharding/unsharding happens inside the
        program.  ``poison=True`` wraps the shard smooth with the fault
        injector (supervisor fault drills).  ``reg_value`` is the plain
        full-tree penalty; its shard-local partial is psummed here.
        """
        mesh, axis = self.mesh, self.data_axis
        n_shards = mesh.shape[axis]
        gradient, rebuild_local = self.gradient, self._rebuild_local

        def _body(carry, *data):
            idx = lax.axis_index(axis)
            template = carry.x if warm_entry else carry
            layout = ShardLayout.for_tree(template, n_shards)
            if warm_entry:
                warm_sh = carry._replace(x=layout.shard(carry.x, idx),
                                         z=layout.shard(carry.z, idx))
                w0_sh = warm_sh.x
            else:
                warm_sh = None
                w0_sh = layout.shard(carry, idx)

            Xl, yl, ml = rebuild_local(*data)
            sm, sl = grid._local_smooth_fns(gradient, Xl, yl, ml, axis,
                                            layout=layout)
            if poison:
                from ..resilience import faults as faults_lib
                sm = faults_lib.poison_smooth(sm)

            def rv_shard(w_sh):
                # elementwise penalties sum over elements; zero pad slots
                # contribute zero, so the psum of shard partials is the
                # exact full-tree value
                return lax.psum(reg_value(w_sh), axis)

            res = agd.run_agd(sm, prox, rv_shard, w0_sh, config,
                              smooth_loss=sl, warm=warm_sh,
                              telemetry_cb=telemetry_cb, axis_name=axis)
            # exit allgather: results speak full trees so donation,
            # checkpoints, and cross-mode resume compose unchanged
            return res._replace(weights=layout.gather(res.weights, axis),
                                final_z=layout.gather(res.final_z, axis))

        run = shard_map(_body, mesh=mesh,
                        in_specs=(P(),) + tuple(self._data_specs),
                        out_specs=P(), check_vma=False)

        def run_bound(carry, data_args):
            return run(carry, *data_args)

        return run_bound


def make_sharded_staged(
    gradient: Gradient,
    X,
    y=None,
    mask=None,
    *,
    mesh: Mesh,
    data_axis: str = mesh_lib.DATA_AXIS,
):
    """``(build, data_args)`` for the sharded-update mode — the staged
    twin of ``dist_smooth.make_dist_smooth_staged`` with a
    :class:`ShardedUpdateBuild` in the build slot.  Accepts the same
    inputs: a ``ShardedBatch`` (preferred) or raw ``(X, y[, mask])``
    sharded on the fly."""
    from ..ops.pallas_kernels import PallasMarginGradient

    if isinstance(gradient, PallasMarginGradient):
        raise ValueError(
            "sharded_update does not compose with the fused Pallas "
            "kernel yet (its tile-aligned relayout assumes the "
            "replicated smooth contract); use the XLA gradient or "
            "sharded_update=False")
    if isinstance(X, mesh_lib.ShardedBatch):
        if y is not None or mask is not None:
            raise ValueError(
                "pass either a ShardedBatch or raw (X, y[, mask]), not both")
        X, y, mask = X
    elif y is None:
        raise ValueError("y is required when X is a raw array")
    if not isinstance(X, (jax.Array, RowShardedCSR)) \
            or not isinstance(y, jax.Array):
        X, y, mask = mesh_lib.shard_batch(mesh, X, y, mask, axis=data_axis)
    build = ShardedUpdateBuild(gradient, X, y, mask, mesh=mesh,
                               data_axis=data_axis)
    return build, build.data_args
