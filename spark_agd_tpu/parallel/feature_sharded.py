"""Feature-dimension (D-axis) sharding — scaling the axis the reference
cannot.

The reference's only answer to a wide model is a bigger broadcast: the
whole weight vector ships to every executor per evaluation, guarded by the
1MB-task-size test (reference Suite:244-259) — at url_combined scale
(D = 3,231,961) that is ~13 MB *per evaluation per executor* over the
network.  The TPU-native inversion: shard the weight vector over the mesh
``model`` axis so each chip holds D/n of it (and of the optimizer state,
and of the column-sliced data), and assemble only the (N,)-vector of
margins with one psum per evaluation.

Layout (classic model-parallel GLM):

- host pre-shards the CSR matrix by column range; each shard's entries are
  re-indexed to local columns and padded to a common nnz so the stacked
  arrays are rectangular (padding value 0.0 at the last row/col slot is
  inert in both products and keeps ids nondecreasing);
- inside ``shard_map``: ``dots_partial = segment_sum(values * w_local[
  col_local], row_ids)`` — each chip's contribution to every row's margin;
  one ``psum`` over ``model`` assembles full margins everywhere (THE only
  collective);
- the per-row loss/multiplier middle (``MarginGradient.dots_loss_and_mult``
  — the same code the row-sharded kernels run, so layouts cannot drift) is
  computed replicated;
- ``grad_local`` lands already sharded: a SORTED column segment-sum over
  each shard's column-sorted entry twin (the ops.sparse CSC rationale;
  scatter-add only when the twin is disabled) — the gradient, prox step,
  and all AT recurrences stay D-sharded with zero further communication;
  elementwise optimizer math partitions over the mesh for free under
  GSPMD.

Cost shape per evaluation: one psum of (N,) — vs the reference's full-D
broadcast + full-D tree-reduce.  For N ≪ D (url_combined: 2.4M rows vs
3.2M features — and any minibatch regime) this is strictly less traffic,
and it is the layout that keeps working when D no longer fits one chip.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .shmap import shard_map

from .. import native
from ..ops.losses import MarginGradient
from ..ops.sparse import CSRMatrix
from . import mesh as mesh_lib


class FeatureShardedBatch(NamedTuple):
    """Column-sharded CSR batch on a mesh.  ``row_ids``/``col_local``/
    ``values`` are (n_shards * nnz_shard,) device arrays sharded over the
    ``model`` axis; ``n_rows``/``n_features``/``d_local`` are static.
    ``positions`` (host array, (n_features,)) maps global column c to its
    padded position ``shard * d_local + local`` — columns are assigned to
    shards by greedy nnz balancing, NOT contiguous ranges, so a power-law
    column distribution (url_combined's regime) cannot pile most entries
    onto one shard.

    Per-shard entries are sorted by row id (padding points at the last
    row), and ``csc_*`` — when built, the default — is each shard's
    entry copy sorted by LOCAL COLUMN, so both the margin segment-sum
    and the gradient's column reduction run with
    ``indices_are_sorted=True`` instead of a scatter-add (the
    ops.sparse CSC-twin rationale, applied to the D-sharded layout)."""

    row_ids: jax.Array
    col_local: jax.Array
    values: jax.Array
    y: jax.Array  # (N,) replicated
    mask: Optional[jax.Array]  # (N,) replicated, or None
    positions: np.ndarray  # host-side column -> padded-position map
    n_rows: int
    n_features: int
    d_local: int  # columns per shard (D padded to n_shards * d_local)
    csc_row_ids: Optional[jax.Array] = None
    csc_col_local: Optional[jax.Array] = None
    csc_values: Optional[jax.Array] = None

    @property
    def has_csc(self) -> bool:
        return self.csc_values is not None


def shard_csr_by_columns(
    indptr, indices, values, n_features: int, y,
    mesh: Mesh, mask=None, axis: str = mesh_lib.MODEL_AXIS,
    with_csc: bool = True,
) -> FeatureShardedBatch:
    """Host-side layout: assign columns to shards in nnz-balanced
    serpentine order, re-index entries to (shard, local), pad shards to a
    common nnz, place on the mesh.  ``with_csc=False`` drops the
    column-sorted gradient twin (halves entry memory, reverts the
    gradient to scatter-add)."""
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    values = np.asarray(values, np.float32)
    if len(indices) and (indices.min() < 0 or indices.max() >= n_features):
        raise ValueError(
            f"column index out of range: [{indices.min()}, {indices.max()}]"
            f" vs n_features={n_features} — refusing a layout that would "
            "silently corrupt the padding tail")
    n_rows = len(indptr) - 1
    counts = np.diff(indptr)
    row_ids = np.repeat(np.arange(n_rows, dtype=np.int32), counts)

    n_shards = mesh.shape[axis]
    d_local = -(-n_features // n_shards)  # ceil

    # Greedy nnz balance: walk columns heaviest-first, placing each on the
    # currently lightest shard with remaining capacity.  Max shard load ≈
    # max(heaviest column, total/n_shards) — the best any column-granular
    # layout can do under power-law occupancy (url_combined's regime).
    # C++ core with bit-identical Python fallback (native.greedy_balance);
    # the pure-Python loop costs seconds at D = 3.2M (native ~7x faster).
    col_nnz = np.bincount(indices, minlength=n_features)
    shard_of_col, local_of_col = native.greedy_balance(
        col_nnz, n_shards, d_local)
    positions = shard_of_col * d_local + local_of_col

    e_shard = shard_of_col[indices]
    e_local = local_of_col[indices].astype(np.int32)

    eorder = np.argsort(e_shard, kind="stable")
    shard_sorted_e = e_shard[eorder]
    starts = np.searchsorted(shard_sorted_e, np.arange(n_shards))
    ends = np.searchsorted(shard_sorted_e, np.arange(n_shards),
                           side="right")
    per_shard = ends - starts
    nnz_shard = max(int(per_shard.max()) if len(values) else 1, 1)

    # Padding points at the last row / last local column (inert 0.0
    # values) so per-shard ids stay nondecreasing for the sorted
    # segment-sums.  Entries within a shard keep original order = sorted
    # by row (stable shard sort of row-sorted input).
    R = np.full((n_shards, nnz_shard), max(n_rows - 1, 0), np.int32)
    C = np.zeros((n_shards, nnz_shard), np.int32)
    V = np.zeros((n_shards, nnz_shard), np.float32)
    if with_csc:
        Rc = np.zeros((n_shards, nnz_shard), np.int32)
        Cc = np.full((n_shards, nnz_shard), d_local - 1, np.int32)
        Vc = np.zeros((n_shards, nnz_shard), np.float32)
    for s in range(n_shards):
        sel = eorder[starts[s]:ends[s]]
        k = len(sel)
        R[s, :k] = row_ids[sel]
        C[s, :k] = e_local[sel]
        V[s, :k] = values[sel]
        if with_csc:  # column-sorted twin of the same entries
            sel_c = sel[np.argsort(e_local[sel], kind="stable")]
            Rc[s, :k] = row_ids[sel_c]
            Cc[s, :k] = e_local[sel_c]
            Vc[s, :k] = values[sel_c]

    spec = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    csc = {}
    if with_csc:
        csc = dict(csc_row_ids=jax.device_put(Rc.reshape(-1), spec),
                   csc_col_local=jax.device_put(Cc.reshape(-1), spec),
                   csc_values=jax.device_put(Vc.reshape(-1), spec))
    return FeatureShardedBatch(
        row_ids=jax.device_put(R.reshape(-1), spec),
        col_local=jax.device_put(C.reshape(-1), spec),
        values=jax.device_put(V.reshape(-1), spec),
        y=jax.device_put(np.asarray(y, np.float32), rep),
        mask=(None if mask is None
              else jax.device_put(np.asarray(mask, np.float32), rep)),
        positions=positions,
        n_rows=n_rows, n_features=int(n_features), d_local=int(d_local),
        **csc)


def shard_weights(w, batch: FeatureShardedBatch, mesh: Mesh,
                  axis: str = mesh_lib.MODEL_AXIS) -> jax.Array:
    """Place a (D,) weight vector D-sharded: scatter into the batch's
    padded positions and shard over ``axis``.  Unused positions stay
    exactly zero through every prox in ``ops.prox`` (all are odd maps
    fixing 0), so ``unshard_weights`` is lossless."""
    n_shards = mesh.shape[axis]
    d_pad = n_shards * batch.d_local
    w = np.asarray(w, np.float32)
    wp = np.zeros(d_pad, np.float32)
    wp[batch.positions] = w
    return jax.device_put(wp, NamedSharding(mesh, P(axis)))


def unshard_weights(w_sharded, batch: FeatureShardedBatch) -> np.ndarray:
    return np.asarray(w_sharded)[batch.positions]


def make_feature_sharded_smooth(
    gradient: MarginGradient,
    batch: FeatureShardedBatch,
    *,
    mesh: Mesh,
    axis: str = mesh_lib.MODEL_AXIS,
) -> Tuple:
    """Build ``(smooth, smooth_loss)`` over a column-sharded batch.

    ``smooth(w_sharded) -> (mean_loss, mean_grad_sharded)`` — the gradient
    comes back with the same D-sharding as the weights, so the whole AGD
    loop runs on sharded state.
    """
    if not isinstance(gradient, MarginGradient):
        raise TypeError(
            "feature sharding needs a margin-form GLM loss "
            f"(MarginGradient); got {type(gradient).__name__}")
    has_mask = batch.mask is not None
    n_rows = batch.n_rows
    d_local = batch.d_local
    if has_mask:
        n_valid = float(np.asarray(jnp.sum(batch.mask > 0)))
    else:
        n_valid = float(n_rows)

    sharded = P(axis)
    rep = P()
    n_csc = 3 if batch.has_csc else 0
    in_specs = (sharded,) * (4 + n_csc) + (rep,) \
        + ((rep,) if has_mask else ())

    @jax.jit
    def _eval(w, row_ids, col_local, values, *rest):
        def body(w_l, r, c, v, *rest_l):
            csc_l, tail = rest_l[:n_csc], rest_l[n_csc:]
            y_r, ms_l = tail[0], tail[1:]
            # this chip's column slice as a local CSR — the ONE sparse
            # kernel implementation (ops.sparse) serves here too; entries
            # are row-sorted and the csc twin column-sorted by layout
            csc_kw = (dict(csc_row_ids=csc_l[0], csc_col_ids=csc_l[1],
                           csc_values=csc_l[2]) if csc_l else {})
            Xl = CSRMatrix(r, c, v, (n_rows, d_local), rows_sorted=True,
                           **csc_kw)
            dots_partial = Xl.matvec(w_l)
            # THE collective: assemble full margins on every chip
            dots = lax.psum(dots_partial, axis)
            per, mult = gradient.dots_loss_and_mult(
                dots, y_r.astype(dots.dtype))
            if ms_l:
                per = per * ms_l[0]
                mult = mult * ms_l[0]
            loss_sum = jnp.sum(per)  # identical on every chip post-psum
            # gradient lands already sharded: a sorted column reduction
            # (csc twin) or scatter into local columns (without it)
            return loss_sum, Xl.rmatvec(mult)

        return shard_map(
            body, mesh=mesh,
            in_specs=in_specs,
            out_specs=(rep, sharded),
            check_vma=False,
        )(w, row_ids, col_local, values, *rest)

    args = (batch.row_ids, batch.col_local, batch.values) \
        + ((batch.csc_row_ids, batch.csc_col_local, batch.csc_values)
           if batch.has_csc else ()) \
        + (batch.y,) + ((batch.mask,) if has_mask else ())

    def smooth(w):
        ls, gs = _eval(w, *args)
        return ls / n_valid, gs / n_valid

    def smooth_loss(w):
        return _eval(w, *args)[0] / n_valid

    return smooth, smooth_loss
