"""Distributed ``smooth`` builders — the ``treeAggregate`` replacement.

The reference's one distributed computation (``applySmooth``, reference
``AcceleratedGradientDescent.scala:192-208``) is: broadcast weights, sum
(loss, grad, count) partials up a depth-2 aggregation tree, divide by count
on the driver.  Here the same contract compiles to XLA collectives over the
mesh, two ways:

- ``mode='shard_map'`` — the explicit path: each device runs the batched
  kernel on its row shard and a single ``lax.psum`` over the ``data`` axis
  combines ``(Σloss, Σgrad, n)``.  This is the direct seqOp/combOp analogue
  (reference ``:197-204``): the kernel is the seqOp (vectorised), the psum
  is every level of the comb tree at once, on ICI.  DP only: weights are
  replicated within the shard_map body.

- ``mode='auto'`` — the GSPMD path: the kernel is written on *global*
  arrays; XLA's partitioner reads the input shardings (rows over ``data``,
  weights replicated or sharded over ``model``) and inserts the reduction
  collectives itself.  This is the mode that also gives tensor parallelism
  for free: shard a softmax ``(D, K)`` weight matrix over ``model`` and the
  two matmuls become sharded MXU ops.

Both return the reference's exact contract: ``smooth(w) -> (mean_loss,
mean_grad)`` with the mean taken over *valid* (unmasked) examples
(reference ``:207``).  The division happens once, after the reduction —
sum-form all the way down, so macro-batch streaming composes (SURVEY §7
hard part 4).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .shmap import shard_map

from ..core import tvec
from ..ops.losses import Gradient
from ..ops.sparse import RowShardedCSR
from . import mesh as mesh_lib


def make_dist_smooth(
    gradient: Gradient,
    X,
    y=None,
    mask=None,
    *,
    mesh: Mesh,
    mode: str = "shard_map",
    data_axis: str = mesh_lib.DATA_AXIS,
) -> Tuple[Callable, Callable]:
    """Build ``(smooth, smooth_loss)`` over mesh-sharded data.

    Preferred input: the ``ShardedBatch`` from ``mesh.shard_batch`` as the
    single ``X`` argument — its padding mask can't be dropped on the floor.
    Raw (X, y) arrays are also accepted and sharded on the fly.
    ``smooth_loss`` is the loss-only evaluation for ``loss_mode='x'`` with
    ``beta >= 1``.
    """
    build, args = make_dist_smooth_staged(
        gradient, X, y, mask, mesh=mesh, mode=mode, data_axis=data_axis)
    return build(*args)


def make_dist_smooth_staged(
    gradient: Gradient,
    X,
    y=None,
    mask=None,
    *,
    mesh: Mesh,
    mode: str = "shard_map",
    data_axis: str = mesh_lib.DATA_AXIS,
):
    """``(build, data_args)`` split of :func:`make_dist_smooth` for jit
    callers: ``data_args`` is the placed batch as a flat pytree to pass
    through ``jax.jit``; ``build(*traced)`` runs inside the trace and
    returns ``(smooth, smooth_loss)`` over the tracers.  Same rationale
    as ``core.smooth.make_smooth_staged`` — data embedded as program
    constants makes XLA compile time scale with the dataset."""
    if isinstance(X, mesh_lib.ShardedBatch):
        if y is not None or mask is not None:
            raise ValueError(
                "pass either a ShardedBatch or raw (X, y[, mask]), not both")
        X, y, mask = X
    elif y is None:
        raise ValueError("y is required when X is a raw array")
    if not isinstance(X, (jax.Array, RowShardedCSR)) \
            or not isinstance(y, jax.Array):
        X, y, mask = mesh_lib.shard_batch(mesh, X, y, mask, axis=data_axis)
    return _staged_builders(gradient, X, y, mask, mesh=mesh, mode=mode,
                            data_axis=data_axis)


def _staged_builders(gradient, X, y, mask, *, mesh, mode, data_axis):
    if isinstance(X, RowShardedCSR):
        if mode != "shard_map":
            raise ValueError(
                "row-sharded CSR data requires mode='shard_map' (the "
                "GSPMD partitioner cannot see through the local "
                "segment-sum's row-id indirection)")
        return _make_shard_map_csr(gradient, X, y, mask, mesh, data_axis)
    if mode == "auto":
        return _make_auto(gradient, X, y, mask)
    if mode == "shard_map":
        return _make_shard_map(gradient, X, y, mask, mesh, data_axis)
    raise ValueError(f"unknown mode {mode!r}")


def _finish(loss_sum, grad_sum, n):
    nf = jnp.asarray(n, loss_sum.dtype)
    return loss_sum / nf, tvec.scale(1.0 / nf, grad_sum)


def _pair_builder(eval_fn, args):
    """The ONE ``(build, data_args)`` shape every mode returns:
    ``build(*traced)`` closes the ``(smooth, smooth_loss)`` contract —
    mean over valid rows, division after the reduction — over the
    traced data, with ``eval_fn(w, *data) -> (Σloss, Σgrad, n)`` as the
    only per-mode ingredient.  One definition so the contract cannot
    drift between the four modes (r5 review)."""

    def build(*a):
        def smooth(w):
            ls, gs, n = eval_fn(w, *a)
            return _finish(ls, gs, n)

        def smooth_loss(w):
            ls, _, n = eval_fn(w, *a)
            return ls / jnp.asarray(n, ls.dtype)

        return smooth, smooth_loss

    return build, args


def psum_scatter_combine(ls, gs, n, data_axis, layout):
    """The reduce-scatter twin of the ``_make_shard_map`` combine below
    (arXiv 2004.13336, "Automatic Cross-Replica Sharding of Weight Update
    in Data-Parallel Training"): the control scalars (Σloss, n) still
    all-reduce — they are O(1) on the wire — but the full-D gradient
    combine becomes one tiled ``lax.psum_scatter`` per leaf, so each
    replica receives only its 1/N shard of the *summed* gradient and the
    weight update that consumes it runs on 1/N of the elements.
    ``layout`` is the ``parallel.sharded_update.ShardLayout`` fixing the
    per-leaf flatten/pad geometry; the matching ``all_gather`` is
    ``ShardLayout.gather``.  Only the sharded-update execution mode uses
    this; the replicated builders in this module keep their plain psum
    and trace bit-identical programs to before it existed."""
    ls = lax.psum(ls, data_axis)
    n = lax.psum(n, data_axis)
    return ls, layout.scatter(gs, data_axis), n


def _make_auto(gradient, X, y, mask):
    """GSPMD: global-array kernel; XLA partitions it from input shardings."""

    def _eval(w, Xa, ya, ma):
        return gradient.batch_loss_and_grad(w, Xa, ya, ma)

    return _pair_builder(_eval, (X, y, mask))


def _make_shard_map_pallas(gradient, X, y, mask, mesh, data_axis):
    """Fused single-HBM-pass kernel under data parallelism.

    The generic shard_map body hands ``PallasMarginGradient`` a traced
    row block, which its ``batch_loss_and_grad`` must decline (in-trace
    padding would re-stage X per evaluation) — so mesh runs used to fall
    back to the XLA two-pass lowering per shard.  This builder removes
    that gap: the global batch is re-laid out ONCE at placement time so
    every shard's slice is tile-aligned — rows per shard padded to a
    multiple of the VMEM-budgeted block, width padded to the lane —
    entirely shard-local (pads only unsharded axes; no collectives),
    and the shard_map body then feeds the fused kernel a ``PaddedDense``
    view of its local slice directly.  One X read per shard per
    evaluation + the same single psum.

    Returns None when the layout does not apply (non-2D/over-wide X,
    or a dtype the kernel does not take); the caller falls back.
    """
    from ..ops.pallas_kernels import (
        _LANE, _SUBLANE, PaddedDense, choose_block_rows,
        fused_margin_loss_grad, _pad_to)

    if not isinstance(X, jax.Array) or X.ndim != 2 \
            or X.dtype not in (jnp.float32, jnp.bfloat16):
        return None
    k = mesh.shape[data_axis]
    n, d = X.shape
    if n % k:
        return None  # shard_batch pads to an even split; anything else
        # is a hand-built batch this layout does not understand
    rl = n // k
    dp = _pad_to(d, _LANE)
    # honor the wrapper's explicit block override (the same knob the
    # single-device prepare() path uses)
    br = gradient._block_rows or choose_block_rows(dp, X.dtype.itemsize)
    if br < _SUBLANE:
        return None  # past the single-pass VMEM ceiling
    rlp = -(-rl // br) * br

    row = P(data_axis)
    xsh = NamedSharding(mesh, P(data_axis, None))
    rsh = NamedSharding(mesh, row)
    if mask is None:
        import numpy as np

        mask = jax.device_put(np.ones(n, np.float32), rsh)

    @functools.partial(jax.jit, out_shardings=(xsh, rsh, rsh))
    def relayout(Xg, yg, mg):
        # pad only the per-shard row tail and the width — both
        # unsharded axes after the (k, rl, d) reshape, so the relayout
        # is shard-local by construction
        X3 = jnp.pad(Xg.reshape(k, rl, d),
                     ((0, 0), (0, rlp - rl), (0, dp - d)))
        y3 = jnp.pad(yg.astype(jnp.float32).reshape(k, rl),
                     ((0, 0), (0, rlp - rl)))
        m3 = jnp.pad(mg.astype(jnp.float32).reshape(k, rl),
                     ((0, 0), (0, rlp - rl)))
        return (X3.reshape(k * rlp, dp), y3.reshape(-1), m3.reshape(-1))

    Xp, yp, mp = relayout(X, y, mask)

    in_specs = (P(), P(data_axis, None), row, row)
    out_specs = (P(), P(), P())

    @functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)
    def _eval(w, Xs, ys, ms):
        from ..ops.losses import _count

        padded = PaddedDense(Xs, ys[:, None], ms[:, None],
                             _count(Xs, ms), rlp, d)
        ls, gs = fused_margin_loss_grad(
            gradient.inner, w, padded, interpret=gradient._interpret,
            block_rows=br)
        dt = jnp.result_type(w)
        ls = lax.psum(ls.astype(dt), data_axis)
        gs = lax.psum(gs.astype(dt), data_axis)
        n_tot = lax.psum(padded.n_valid, data_axis)
        return ls, gs, n_tot

    return _pair_builder(_eval, (Xp, yp, mp))


def _make_shard_map(gradient, X, y, mask, mesh, data_axis):
    """Explicit SPMD: per-shard kernel + one psum — seqOp/combOp in one op."""
    from ..ops.pallas_kernels import PallasMarginGradient

    if isinstance(gradient, PallasMarginGradient):
        built = _make_shard_map_pallas(gradient, X, y, mask, mesh,
                                       data_axis)
        if built is not None:
            return built
    has_mask = mask is not None
    row = P(data_axis)
    xspec = P(data_axis, *([None] * (X.ndim - 1)))

    in_specs = (P(), xspec, row) + ((row,) if has_mask else ())
    out_specs = (P(), P(), P())

    @functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)
    def _eval(w, Xs, ys, *ms):
        m = ms[0] if has_mask else None
        ls, gs, n = gradient.batch_loss_and_grad(w, Xs, ys, m)
        # The entire comb tree of the reference's treeAggregate, as one
        # ICI all-reduce (SURVEY §2.2 treeAggregate → psum).
        ls = lax.psum(ls, data_axis)
        gs = tvec.tmap(lambda g: lax.psum(g, data_axis), gs)
        n = lax.psum(n, data_axis)
        return ls, gs, n

    return _pair_builder(_eval, (X, y, mask) if has_mask else (X, y))


def csr_shard_sums(gradient, X, y, mask, mesh, data_axis,
                   with_grad: bool = True, n_lanes: bool = False):
    """One distributed (Σloss, Σgrad, n) pass over a ``RowShardedCSR``.

    The seqOp/combOp core shared by the in-memory mesh path
    (:func:`_make_shard_map_csr`) and the mesh CSR *streaming* path
    (``data.streaming``): each device reconstructs its entry slice as a
    local ``CSRMatrix`` (``RowShardedCSR.local_csr``), runs the same
    batched kernel as the single-device sparse path, and one psum
    combines the sums.  ``with_grad=False`` psums only (loss, n) — the
    unused per-shard gradient (the size-D rmatvec) is dead code inside
    the enclosing jit and vanishes.  ``n_lanes=True`` takes a STACKED
    weight leading axis (K lanes, replicated) and vmaps the kernel over
    it inside the body — the local CSR reconstruction and the psum are
    shared across lanes; the count (mask-only, lane-invariant) psums
    once.  May be called inside a jit trace (the streaming kernels do);
    the shard_map wrapper is created at trace time, once per shape.
    """
    if mask is None:
        raise ValueError(
            "RowShardedCSR requires its padding mask; build the batch "
            "with parallel.mesh.shard_csr_batch")
    row = P(data_axis)
    n_csc = 3 if X.has_csc else 0
    in_specs = (P(),) + (row,) * (5 + n_csc)
    out_specs = (P(), P(), P()) if with_grad else (P(), P())

    def _body(w, rid, cid, val, ys, ms, *csc):
        Xl = X.local_csr(rid, cid, val, *csc)
        if n_lanes:
            ls, gs, n = jax.vmap(
                lambda wv: gradient.batch_loss_and_grad(wv, Xl, ys, ms)
            )(w)
            n = n[0]  # count depends only on the mask: identical lanes
        else:
            ls, gs, n = gradient.batch_loss_and_grad(w, Xl, ys, ms)
        ls = lax.psum(ls, data_axis)
        n = lax.psum(n, data_axis)
        if not with_grad:
            return ls, n
        gs = tvec.tmap(lambda g: lax.psum(g, data_axis), gs)
        return ls, gs, n

    return functools.partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False)(_body)


def csr_shard_args(X: RowShardedCSR, y, mask) -> tuple:
    """The flat argument tuple :func:`csr_shard_sums`'s in_specs are laid
    out for — ONE definition, used by every call site, so the spec/arg
    alignment cannot silently diverge."""
    return (X.row_ids, X.col_ids, X.values, y, mask) + (
        (X.csc_row_ids, X.csc_col_ids, X.csc_values) if X.has_csc else ())


def _make_shard_map_csr(gradient, X, y, mask, mesh, data_axis):
    """Sparse DP: per-device local CSR kernel + the same single psum.

    Each device reconstructs its entry slice as an ordinary local
    ``CSRMatrix`` (``RowShardedCSR.local_csr``) of shape
    ``(rows_per_shard, D)`` and runs the SAME batched kernel as the
    single-device sparse path — the reference's any-Vector ``seqOp``
    capability (``AcceleratedGradientDescent.scala:196-204``) on a mesh.
    The mask is mandatory: per-shard row padding must be excluded from
    the (loss, grad, count) sums.
    """
    _eval = csr_shard_sums(gradient, X, y, mask, mesh, data_axis)
    return _pair_builder(_eval, csr_shard_args(X, y, mask))
