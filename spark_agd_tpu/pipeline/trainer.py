"""Continuous epoch trainer — the training half of the pipeline loop.

One :class:`ContinuousTrainer` owns a long-lived supervised fit broken
into epochs: each ``run_epoch(X, y)`` call warm-starts from the
previous epoch's weights, runs ``run_agd_supervised`` over that
epoch's minibatch (preemption-safe via ``AutoCheckpointer``), and
publishes the result to ``serve.registry`` as a CANDIDATE generation
through the manifest commit protocol — published means durably
committed, NOT serving; the canary/promotion half decides whether
serving HEAD moves (``pipeline.canary`` / ``pipeline.promote``).

Compile-once epochs: the staged ``build`` closure from
``core.smooth.make_smooth_staged`` closes only over the gradient
object, so ONE build is reused across epochs with each epoch's
prepared arrays passed through jit as arguments, and one shared
``seg_cache`` keeps every epoch after the first compile-free (same
shape ⇒ same program).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Optional

from ..core import agd
from ..core import smooth as smooth_lib
from ..resilience.autockpt import AutoCheckpointer
from ..resilience.supervisor import (ResiliencePolicy,
                                     run_agd_supervised)


@dataclasses.dataclass
class EpochResult:
    """One epoch's outcome: the published candidate and its fit."""

    epoch: int
    generation: int        # the published candidate generation
    final_loss: float
    weights: Any           # the CLEAN post-epoch weights (warm start)
    result: Any            # the epoch's SupervisedResult ledger


class ContinuousTrainer:
    """See module docstring.

    ``make_model(weights)`` turns an epoch's weight vector into the
    servable model the registry publishes.  ``weight_fault(epoch,
    weights)`` (optional, drill-only) corrupts the PUBLISHED candidate
    of matching epochs while the warm-start chain keeps the clean
    weights — the fault-injection hook ``tools/pipeline_drill.py``
    forces a failed canary with.
    """

    def __init__(self, registry, gradient, *,
                 prox: Callable, reg_value: Callable,
                 w0, config: Optional[agd.AGDConfig] = None,
                 make_model: Callable[[Any], Any],
                 policy: Optional[ResiliencePolicy] = None,
                 telemetry=None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_keep: int = 2,
                 weight_fault: Optional[Callable] = None):
        self.registry = registry
        self.gradient = gradient
        self.prox = prox
        self.reg_value = reg_value
        self.config = config or agd.AGDConfig()
        self.make_model = make_model
        self.policy = policy
        self.telemetry = telemetry
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.checkpoint_keep = max(1, int(checkpoint_keep))
        self.weight_fault = weight_fault
        self.weights = w0
        self.epoch = 0
        self.total_iters = 0
        self._build = None            # one build, every epoch
        self._seg_cache: dict = {}    # one cache, every epoch

    def run_epoch(self, X, y, mask=None) -> EpochResult:
        """Run one warm-started epoch over ``(X, y)`` and publish the
        result as the next candidate generation.  Returns the epoch's
        :class:`EpochResult`; the supervisor's failure taxonomy
        (transient retry, numeric rollback, preemption resume) applies
        unchanged inside the epoch."""
        self.epoch += 1
        epoch = self.epoch
        span = (self.telemetry.trace_span("pipeline_epoch",
                                          epoch=epoch, tool="pipeline")
                if self.telemetry is not None else None)
        with span if span is not None else contextlib.nullcontext():
            build, dargs = smooth_lib.make_smooth_staged(
                self.gradient, X, y, mask)
            if self._build is None:
                self._build = build
            checkpointer = self._checkpointer(epoch)
            result = run_agd_supervised(
                prox=self.prox, reg_value=self.reg_value,
                w0=self.weights, config=self.config,
                policy=self.policy, telemetry=self.telemetry,
                checkpointer=checkpointer,
                staged=(self._build, dargs),
                seg_cache=self._seg_cache,
                stream_iterations=False)
            return self._publish(epoch, span, result)

    def run_epoch_streamed(self, dataset, *, prefetch: int = 0,
                           stream_every_batches: Optional[int] = None,
                           mesh=None, pad_to: Optional[int] = None,
                           on_commit: Optional[Callable] = None
                           ) -> EpochResult:
        """Run one warm-started epoch over a ``data.streaming.
        StreamingDataset`` — the larger-than-HBM twin of
        :meth:`run_epoch`: the smooth streams macro-batches
        (``make_streaming_smooth``) and the supervisor drives the HOST
        AGD loop (``driver="host"`` — a streamed smooth cannot trace
        into jit).  The full failure taxonomy applies unchanged, plus
        the data-plane hardening the dataset was built with (retries,
        shard quarantine, read timeouts).

        ``stream_every_batches`` (with ``checkpoint_path`` set) arms
        MID-EPOCH checkpointing: a ``StreamCheckpoint`` commits the
        fold's cursor every N batches, so a preemption mid-pass resumes
        from the last committed batch instead of the epoch boundary —
        bit-identical to the uninterrupted epoch.  ``on_commit(count)``
        is the drill's kill hook.  ``prefetch`` is the background
        ingest depth of :func:`~spark_agd_tpu.data.streaming.
        fold_stream`."""
        from ..data import streaming

        self.epoch += 1
        epoch = self.epoch
        span = (self.telemetry.trace_span(
            "pipeline_epoch", epoch=epoch, tool="pipeline",
            streamed=True) if self.telemetry is not None else None)
        with span if span is not None else contextlib.nullcontext():
            checkpointer = self._checkpointer(epoch)
            stream_ckpt = None
            if checkpointer is not None and stream_every_batches:
                stream_ckpt = streaming.StreamCheckpoint(
                    checkpointer,
                    every_batches=int(stream_every_batches),
                    on_commit=on_commit)
            sm, sl = streaming.make_streaming_smooth(
                self.gradient, dataset, mesh=mesh, pad_to=pad_to,
                prefetch=prefetch, stream_ckpt=stream_ckpt,
                telemetry=self.telemetry)
            result = run_agd_supervised(
                smooth=sm, smooth_loss=sl, prox=self.prox,
                reg_value=self.reg_value, w0=self.weights,
                config=self.config, policy=self.policy,
                telemetry=self.telemetry, checkpointer=checkpointer,
                driver="host", stream_iterations=False)
            return self._publish(epoch, span, result)

    def _checkpointer(self, epoch: int) -> Optional[AutoCheckpointer]:
        if self.checkpoint_path is None:
            return None
        return AutoCheckpointer(
            f"{self.checkpoint_path}.e{epoch:03d}.npz",
            every_iters=(self.checkpoint_every
                         or self.config.num_iterations),
            keep=self.checkpoint_keep,
            telemetry=self.telemetry)

    def _publish(self, epoch: int, span, result) -> EpochResult:
        """The shared epoch tail: warm-start carry, candidate publish
        through the manifest commit protocol, span annotation."""
        self.weights = result.weights
        self.total_iters += result.num_iters
        final_loss = (float(result.loss_history[-1])
                      if len(result.loss_history) else float("nan"))
        publish_w = result.weights
        if self.weight_fault is not None:
            publish_w = self.weight_fault(epoch, publish_w)
        generation = self.registry.publish(
            self.make_model(publish_w),
            converged=result.converged,
            prior_iters=self.total_iters)
        if span is not None:
            span.note(generation=generation, final_loss=final_loss,
                      iters=result.num_iters,
                      retries=result.retries,
                      rollbacks=result.rollbacks)
        return EpochResult(epoch=epoch, generation=generation,
                           final_loss=final_loss,
                           weights=result.weights, result=result)
