"""The continuous-learning pipeline: train → publish → canary →
promote/rollback as ONE supervised loop.

The training plane (``resilience.supervisor`` driving the AGD core)
and the serving plane (``serve.registry`` hot swaps) speak the same
CRC-manifested generation protocol but, before this package, never to
each other — a human had to carry weights across.  This package closes
the loop, DeepSpark-style (a driver continuously publishing parameter
updates to serving workers on a fixed cadence), with the gate
discipline the rest of the repo already enforces:

- :class:`~.trainer.ContinuousTrainer` runs warm-started, preemption-
  safe epochs over minibatches and publishes every epoch's weights as
  a candidate generation;
- :class:`~.canary.CanaryController` shadow-serves the candidate on a
  slice of live traffic (a second ``ServeEngine`` beside HEAD) and
  grades it on held-out quality AND shadow latency
  (``obs.perfgate.gate_promotion``);
- :class:`~.promote.Promoter` turns the canary verdict into a typed
  decision — ``promoted`` / ``rejected`` / ``rolled_back`` — where a
  post-repoint failure triggers automatic rollback to the previous
  verifiable generation (``serve.registry.repoint``), flight-recorded
  and emitted as the ``rollback_generation`` recovery action.

Everything rides the existing trace/telemetry machinery: one trace
tree tells the whole train→publish→canary→promote→rollback story
(``tools/agd_trace.py``), and ``tools/pipeline_drill.py`` is the
acceptance drill.  See ``docs/CONTINUOUS.md``.
"""

from .trainer import ContinuousTrainer, EpochResult
from .canary import CanaryController, CanaryReport
from .promote import Promoter, PromotionDecision

__all__ = [
    "ContinuousTrainer", "EpochResult",
    "CanaryController", "CanaryReport",
    "Promoter", "PromotionDecision",
]
