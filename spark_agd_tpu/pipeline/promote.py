"""Typed promotion decisions — the last stage of the pipeline loop.

``Promoter.decide(report)`` turns one :class:`~.canary.CanaryReport`
into exactly one of three decisions, each emitted as a ``promotion``
record inside a ``promotion`` trace span:

- ``rejected``: the canary verdict was not a pass (gate failure or
  refusal) — serving HEAD never moves, the evidence rides the record;
- ``promoted``: the gate passed, ``serve.registry.repoint`` moved HEAD
  to the candidate (atomic manifest repoint + engine hot swap), and
  the optional ``post_check`` against the LIVE generation held;
- ``rolled_back``: the post-repoint check FAILED — the promoter walks
  ``registry.previous()`` back to the prior verifiable generation,
  repoints HEAD there, emits the ``rollback_generation`` recovery
  action, and flight-dumps the telemetry ring (the crash-flight-
  recorder doctrine: a promotion that had to be undone is an incident
  worth a post-mortem artifact).

``post_check(loaded) -> (ok, reason)`` is the promoter's last line of
defense — it runs AFTER the repoint, against the generation that is
actually serving, so evidence the canary could not see (a fault-
injected quality lie, a torn read that only manifests on load) still
cannot stay in production.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Callable, Optional, Tuple

from ..obs import flight as flight_lib
from ..utils.checkpoint import CheckpointCorruptError
from .canary import CanaryReport


@dataclasses.dataclass
class PromotionDecision:
    """One typed decision: what happened and where HEAD ended up."""

    decision: str                       # promoted | rejected | rolled_back
    candidate_generation: int
    from_generation: Optional[int]      # HEAD before the decision
    to_generation: Optional[int]        # HEAD after (None: never moved
    #                                     and nothing to roll back to)
    gate_status: str
    record: dict                        # the emitted promotion record


class Promoter:
    """See module docstring."""

    def __init__(self, registry, engine=None, *, telemetry=None,
                 post_check: Optional[
                     Callable[..., Tuple[bool, str]]] = None):
        self.registry = registry
        self.engine = engine
        self.telemetry = telemetry
        self.post_check = post_check

    def decide(self, report: CanaryReport) -> PromotionDecision:
        span = (self.telemetry.trace_span(
                    "promotion",
                    candidate_generation=int(report.generation),
                    tool="pipeline")
                if self.telemetry is not None else None)
        with span if span is not None else contextlib.nullcontext():
            current = self.registry.current
            from_gen = (current.generation
                        if current is not None else None)
            decision = self._decide_locked(report, from_gen)
            if span is not None:
                span.note(decision=decision.decision,
                          to_generation=decision.to_generation)
            return decision

    def _decide_locked(self, report: CanaryReport,
                       from_gen: Optional[int]) -> PromotionDecision:
        evidence = {
            "verdict": report.verdict,
            "canary_refusals": list(report.refusals),
        }
        if report.gate is not None:
            evidence["gate_failures"] = list(report.gate.failures)

        if report.verdict != "pass":
            gate_status = ("refused" if report.verdict == "refused"
                           else "failed")
            return self._emit("rejected", report, from_gen, from_gen,
                              gate_status, evidence,
                              reason="canary verdict was "
                                     f"{report.verdict!r}")

        self.registry.repoint(report.generation, engine=self.engine)
        ok, reason = (True, "")
        if self.post_check is not None:
            ok, reason = self.post_check(self.registry.current)
        if ok:
            return self._emit("promoted", report, from_gen,
                              report.generation, "passed", evidence,
                              reason="canary gate and post-promotion "
                                     "check passed")

        # the candidate is LIVE and bad: prefer the generation that was
        # serving before the repoint, else walk back to the previous
        # verifiable generation, skipping targets that fail to load
        evidence["post_check"] = reason
        target = (from_gen if from_gen and from_gen != report.generation
                  else self.registry.previous(report.generation))
        rolled_to = None
        while target is not None:
            try:
                self.registry.repoint(target, engine=self.engine)
                rolled_to = target
                break
            except (LookupError, CheckpointCorruptError):
                target = self.registry.previous(target)
        if self.telemetry is not None:
            rec_fields = {"from_generation": int(report.generation),
                          "reason": reason[:200],
                          "source": "pipeline.promote",
                          "tool": "pipeline"}
            if rolled_to is not None:
                rec_fields["generation"] = int(rolled_to)
            self.telemetry.recovery(action="rollback_generation",
                                    **rec_fields)
            flight_lib.dump_on_failure(self.telemetry,
                                       "promotion_rollback")
        return self._emit("rolled_back", report, from_gen, rolled_to,
                          "failed", evidence,
                          reason="post-promotion check failed: "
                                 + reason[:160])

    def _emit(self, decision: str, report: CanaryReport,
              from_gen: Optional[int], to_gen: Optional[int],
              gate_status: str, evidence: dict,
              *, reason: str) -> PromotionDecision:
        fields = {
            "candidate_generation": int(report.generation),
            "from_generation": from_gen,
            "gate_status": gate_status, "evidence": evidence,
            "reason": reason, "source": "pipeline.promote",
            "tool": "pipeline",
        }
        if to_gen is not None:
            fields["to_generation"] = int(to_gen)
        if report.epoch is not None:
            fields["epoch"] = int(report.epoch)
        if report.refusals:
            fields["refusals"] = list(report.refusals)
        if self.telemetry is not None:
            rec = self.telemetry.promotion(decision=decision, **fields)
        else:
            from ..obs import schema
            rec = schema.promotion_record("(untracked)", decision,
                                          **fields)
        return PromotionDecision(
            decision=decision,
            candidate_generation=int(report.generation),
            from_generation=from_gen, to_generation=to_gen,
            gate_status=gate_status, record=rec)
