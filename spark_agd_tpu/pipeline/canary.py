"""Canary controller — shadow-serve a candidate beside HEAD and grade it.

A published candidate generation must EARN serving HEAD.  The
controller binds the candidate into a second ``ServeEngine`` (same
bucket ladder, so the persistent compile cache makes re-warmups cheap)
fed by its own ``MicroBatchQueue``, and mirrors a configurable slice
of live traffic to it: callers submit through
:meth:`CanaryController.submit`, every request serves from HEAD as
usual, and a deterministic ``slice_fraction`` of them is ALSO enqueued
on the shadow leg.  The shadow futures are never returned to callers —
a slow or broken candidate can never touch a live response.

The verdict comes from ``obs.perfgate.gate_promotion`` over the
evidence the window collected: held-out loss of candidate vs HEAD
(``models.evaluation.log_loss``, relative threshold) AND shadow
p50/p99 vs HEAD's percentiles (the serving-SLO thresholds).  Torn
candidates (``CheckpointCorruptError``), spec mismatches (the engine's
``ServeSpecMismatch`` refusal, checked BEFORE compiling a shadow
engine), thin shadow traffic, and contention-flagged windows all
refuse rather than judge.  Every window emits one ``canary`` record
inside a ``canary`` trace span, so the decision evidence rides the
same trace tree as the epoch that produced the candidate.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional

from ..models.evaluation import log_loss
from ..obs import perfgate
from ..serve.engine import ServeEngine, spec_of
from ..serve.queue import MicroBatchQueue
from ..utils.checkpoint import CheckpointCorruptError


@dataclasses.dataclass
class CanaryReport:
    """One canary window's outcome — what ``Promoter.decide`` acts on."""

    generation: int                # the candidate
    baseline_generation: Optional[int]
    verdict: str                   # "pass" | "fail" | "refused"
    record: dict                   # the emitted canary record
    gate: Optional[perfgate.PromotionGateResult]
    refusals: List[str]
    epoch: Optional[int] = None


class CanaryController:
    """See module docstring.

    ``holdout=(Xv, yv)`` is the quality leg's held-out set;
    ``contention_check()`` (optional) flags a noisy measurement window
    (the scaling observatory's sentinel doctrine) — a flagged window
    refuses the latency leg instead of grading on it.
    """

    def __init__(self, registry, engine, queue, *, telemetry=None,
                 holdout=None,
                 slice_fraction: float = 0.25,
                 quality_threshold: float =
                 perfgate.DEFAULT_QUALITY_THRESHOLD,
                 thresholds: Optional[dict] = None,
                 min_shadow_requests: int =
                 perfgate.DEFAULT_MIN_SHADOW_REQUESTS,
                 contention_check: Optional[Callable[[], bool]] = None):
        if not 0.0 < slice_fraction <= 1.0:
            raise ValueError(
                f"slice_fraction must be in (0, 1], got {slice_fraction}")
        self.registry = registry
        self.engine = engine
        self.queue = queue
        self.telemetry = telemetry
        self.holdout = holdout
        self.slice_fraction = float(slice_fraction)
        self.quality_threshold = float(quality_threshold)
        self.thresholds = dict(thresholds or {})
        self.min_shadow_requests = int(min_shadow_requests)
        self.contention_check = contention_check
        self._lock = threading.Lock()
        self._reset_window()

    def _reset_window(self) -> None:
        self._candidate = None          # LoadedModel under canary
        self._shadow_engine = None
        self._shadow_queue = None
        self._shadow_futures: List = []
        self._seen = 0
        self._mirrored = 0
        self._span = None
        self._epoch: Optional[int] = None
        self._quality_override: Optional[float] = None
        self._preflight_refusals: List[str] = []

    @property
    def active(self) -> bool:
        return self._shadow_queue is not None

    @property
    def shadow_count(self) -> int:
        """Requests mirrored to the shadow leg so far this window —
        what a caller polls to know the window has enough evidence
        (``min_shadow_requests``) to close."""
        with self._lock:
            return self._mirrored

    # -- the live traffic path --------------------------------------------
    def submit(self, x, op: str = "predict"):
        """Submit one live request: always served from HEAD (the
        returned future), and mirrored to the active shadow leg when
        the deterministic slice counter says so.  Shadow admission
        failures (``ServeOverloaded``) silently drop the MIRROR — the
        live request is already admitted and must not feel the
        candidate."""
        future = self.queue.submit(x, op)
        with self._lock:
            sq = self._shadow_queue
            if sq is not None:
                self._seen += 1
                # mirror when the running fraction falls behind the
                # target slice — deterministic, no RNG in the hot path
                if self._mirrored < self._seen * self.slice_fraction:
                    try:
                        self._shadow_futures.append(sq.submit(x, op))
                        self._mirrored += 1
                    except (RuntimeError, ValueError):
                        pass
        return future

    # -- the canary window -------------------------------------------------
    def start_canary(self, generation: int, *,
                     epoch: Optional[int] = None,
                     quality_override: Optional[float] = None) -> bool:
        """Open a canary window for ``generation``: load and verify the
        candidate, refuse torn targets and spec mismatches pre-flight
        (no shadow engine is built for them), else bind a shadow
        engine+queue and start mirroring.  Returns True when shadow
        serving actually started; False means the window is already
        decided (``finish_canary`` will emit the refused record).

        ``quality_override`` (drill-only fault injection) replaces the
        candidate's measured held-out loss in the EVIDENCE, stamped
        ``quality_fault_injected`` — how ``tools/pipeline_drill.py``
        slips a bad candidate past the canary to exercise the
        post-promotion rollback path."""
        if self.active or self._candidate is not None:
            raise RuntimeError("a canary window is already open — "
                               "finish_canary() first")
        self._epoch = epoch
        self._quality_override = quality_override
        if self.telemetry is not None:
            self._span = self.telemetry.trace_span(
                "canary", generation=int(generation), tool="pipeline")
            self._span.__enter__()
        try:
            loaded = self.registry.load(int(generation))
        except (LookupError, CheckpointCorruptError) as e:
            self._preflight_refusals.append(
                f"candidate g{generation} failed verification: "
                f"{str(e)[:160]}")
            self._candidate = ("refused", int(generation), None)
            return False
        cand_spec = spec_of(loaded.model)
        if cand_spec != self.engine.spec:
            self._preflight_refusals.append(
                f"candidate g{generation} spec mismatch vs serving "
                "HEAD — refusing to shadow-serve a different model "
                "family")
            self._candidate = ("refused", int(generation),
                              dataclasses.asdict(cand_spec))
            return False
        self._candidate = loaded
        self._shadow_engine = ServeEngine(
            loaded.model, generation=loaded.generation,
            max_batch=self.engine.max_batch, telemetry=self.telemetry)
        with self._lock:
            self._shadow_queue = MicroBatchQueue(
                self._shadow_engine, telemetry=self.telemetry).start()
        return True

    def finish_canary(self) -> CanaryReport:
        """Close the window: drain the shadow leg, collect both legs'
        latency summaries and the held-out quality of candidate vs
        HEAD, grade everything through ``gate_promotion``, and emit the
        ``canary`` record.  The report carries the gate result for
        ``Promoter.decide``."""
        if self._candidate is None:
            raise RuntimeError("no canary window open")
        baseline = self.registry.current
        base_gen = baseline.generation if baseline is not None else None
        fields: dict = {
            "slice_fraction": self.slice_fraction,
            "quality_threshold": self.quality_threshold,
            "source": "pipeline.canary", "tool": "pipeline",
        }
        if base_gen is not None:
            fields["baseline_generation"] = int(base_gen)
        if self._epoch is not None:
            fields["epoch"] = int(self._epoch)

        if isinstance(self._candidate, tuple):
            # pre-flight refusal: no shadow leg ever ran
            _, generation, cand_spec = self._candidate
            fields.update(shadow_requests=0,
                          refusals=list(self._preflight_refusals))
            if cand_spec is not None:
                fields["candidate_spec"] = cand_spec
                fields["baseline_spec"] = dataclasses.asdict(
                    self.engine.spec)
            return self._close("refused", generation, fields, None)

        loaded = self._candidate
        with self._lock:
            sq, self._shadow_queue = self._shadow_queue, None
            futures = self._shadow_futures
        for f in futures:
            try:
                f.result(timeout=30.0)
            except Exception:
                pass  # the summary's error count carries the evidence
        shadow = sq.latency_summary()
        if self.telemetry is not None:
            sq.emit_latency()
        sq.stop()
        head = self.queue.latency_summary()

        fields["shadow_requests"] = int(shadow.get("requests", 0))
        for metric in ("p50_ms", "p99_ms"):
            if metric in shadow:
                fields[metric] = shadow[metric]
            if metric in head:
                fields[f"baseline_{metric}"] = head[metric]
        if self.contention_check is not None:
            fields["contention_flagged"] = bool(self.contention_check())
        if self.holdout is not None and baseline is not None:
            Xv, yv = self.holdout
            qb = float(log_loss(
                baseline.model.predict_proba(Xv), yv))
            qc = float(log_loss(
                loaded.model.predict_proba(Xv), yv))
            if self._quality_override is not None:
                qc = float(self._quality_override)
                fields["quality_fault_injected"] = True
            fields.update(quality_baseline=qb, quality_candidate=qc,
                          quality_delta=(qc - qb))

        gate = perfgate.gate_promotion(
            [dict(fields, kind="canary",
                  generation=int(loaded.generation))],
            quality_threshold=self.quality_threshold,
            thresholds=self.thresholds,
            min_shadow_requests=self.min_shadow_requests)
        verdict = ("refused" if gate.refused
                   else "fail" if gate.failures else "pass")
        if gate.refusals:
            fields["refusals"] = list(gate.refusals)
        fields["quality_verdict"] = self._leg_verdict(
            gate, ("holdout_loss",))
        fields["latency_verdict"] = self._leg_verdict(
            gate, perfgate.PROMOTION_LATENCY_METRICS)
        return self._close(verdict, loaded.generation, fields, gate)

    @staticmethod
    def _leg_verdict(gate, metrics) -> str:
        legs = [d for d in gate.deltas if d.metric in metrics]
        if not legs:
            return "refused"
        return ("fail" if any(d.status == "regression" for d in legs)
                else "pass")

    def _close(self, verdict: str, generation: int, fields: dict,
               gate) -> CanaryReport:
        refusals = list(fields.get("refusals", []))
        if self.telemetry is not None:
            rec = self.telemetry.canary(
                generation=int(generation), verdict=verdict, **fields)
        else:
            from ..obs import schema
            rec = schema.canary_record("(untracked)", int(generation),
                                       verdict, **fields)
        report = CanaryReport(
            generation=int(generation),
            baseline_generation=fields.get("baseline_generation"),
            verdict=verdict, record=rec, gate=gate,
            refusals=refusals, epoch=self._epoch)
        span = self._span
        self._reset_window()
        if span is not None:
            span.note(verdict=verdict)
            span.__exit__(None, None, None)
        return report
